"""Runtime environments: per-task/actor/job execution contexts.

Analog of the reference's runtime_env machinery (reference:
python/ray/_private/runtime_env/ — working_dir.py, py_modules.py,
packaging.py URI cache, plugin.py; agent materializes envs per node).
TPU-native simplifications: packages travel through the control-plane KV
(content-addressed zips) instead of a dedicated agent protocol, and
materialization happens lazily in the worker with a node-shared
content-addressed cache.

Supported fields:
  env_vars     {str: str}   applied around execution
  working_dir  path/zip     shipped, extracted, becomes cwd + sys.path[0]
  py_modules   [paths]      shipped, extracted, prepended to sys.path
  pip          [requirements]  content-addressed package env built once
               per node (pip install --target into the shared cache) and
               prepended to sys.path — the venv-equivalent for in-process
               workers (reference: runtime_env/pip.py builds a virtualenv
               and spawns the worker inside it; our workers already run,
               so the env is import-path scoped instead).  Gated: rejected
               unless RAY_TPU_ALLOW_PKG_INSTALL=1.  With
               RAY_TPU_WHEELHOUSE=<dir> the install is fully offline
               (--no-index --find-links), which is also how it is tested.
  uv           [requirements]  same content-addressed target-dir model
               as pip, installed by the `uv` binary (worker-local PATH /
               RAY_TPU_UV_BIN, falling back to the driver's setting).
               Same gate and wheelhouse behavior as pip.
  conda        str (existing env NAME or PREFIX) or environment.yml-style
               dict (created once per content hash).  The env's
               site-packages is import-path scoped into the worker — the
               reference re-execs workers inside the env, so only
               ABI-compatible (same python minor) envs are accepted.
               Same RAY_TPU_ALLOW_PKG_INSTALL gate.

pip/uv/conda are mutually exclusive, as in the reference.
"""

from __future__ import annotations

import hashlib
import io
import logging
import os
import sys
import threading
import zipfile
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

KV_NS = "runtime_env_packages"
CACHE_ROOT = os.environ.get("RAY_TPU_RTENV_CACHE",
                            "/dev/shm/ray_tpu/rtenv-cache")
from .config import cfg as _cfg

MAX_PACKAGE_BYTES = _cfg().rtenv_max_bytes
_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules"}

_lock = threading.Lock()
_materialized: Dict[str, str] = {}  # pkg hash -> extracted dir


def validate(env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    env = dict(env or {})
    unknown = set(env) - {"env_vars", "working_dir", "py_modules", "pip",
                          "uv", "conda", "config", "container", "image_uri"}
    if unknown:
        raise ValueError(f"unsupported runtime_env fields: {sorted(unknown)}")
    if env.get("image_uri"):
        # sugar (reference: runtime_env/image_uri.py ImageURIPlugin):
        # image_uri="img" == container={"image": "img"}
        if env.get("container"):
            raise ValueError("image_uri and container are mutually "
                             "exclusive (image_uri is shorthand)")
        env["container"] = {"image": env.pop("image_uri")}
    if sum(1 for k in ("pip", "uv", "conda") if env.get(k)) > 1:
        raise ValueError("pip, uv, and conda are mutually exclusive "
                         "(reference: runtime_env validation)")
    if env.get("pip") or env.get("uv") or env.get("conda"):
        if not _cfg().allow_pkg_install:
            raise ValueError(
                "runtime_env pip/uv/conda installs are disabled in this "
                "deployment (set RAY_TPU_ALLOW_PKG_INSTALL=1 to enable)")
    c = env.get("container")
    if c:
        if not isinstance(c, dict) or not isinstance(c.get("image"), str) \
                or not c["image"]:
            raise ValueError("container must be {'image': str, "
                             "'run_options': [str, ...]?}")
        unknown_c = set(c) - {"image", "run_options", "runtime"}
        if unknown_c:
            raise ValueError(
                f"unsupported container fields: {sorted(unknown_c)}")
        if "runtime" in c and not isinstance(c["runtime"], str):
            raise ValueError("container runtime must be a string path")
        opts = c.get("run_options", [])
        if not isinstance(opts, (list, tuple)) or \
                not all(isinstance(o, str) for o in opts):
            # a bare string is an iterable of 1-char strings and would
            # splat into per-character argv entries downstream
            raise ValueError(
                "container run_options must be a list of strings")
        if env.get("pip") or env.get("uv") or env.get("conda"):
            raise ValueError("container excludes pip/uv/conda — bake "
                             "dependencies into the image (reference: "
                             "image_uri.py validation)")
        if not _cfg().allow_pkg_install:
            # image pulls are egress, gated exactly like pip installs
            raise ValueError(
                "container runtime_envs are disabled in this deployment "
                "(pulling images needs egress; set "
                "RAY_TPU_ALLOW_PKG_INSTALL=1 to enable)")
    ev = env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise ValueError("env_vars must be {str: str}")
    return env


def container_spec(env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The validated container spec of a prepared env (None when absent)."""
    return (env or {}).get("container") or None


def resolve_container_runtime(explicit: Optional[str] = None) -> str:
    """Container runtime resolution (reference: image_uri.py uses podman):
    explicit > RAY_TPU_CONTAINER_RUNTIME > podman > docker on PATH; loud
    failure when none exists — a container env must never silently run
    un-containerized."""
    import shutil as _shutil

    for cand in (explicit, os.environ.get("RAY_TPU_CONTAINER_RUNTIME")):
        if not cand:
            continue
        if os.path.isfile(cand) and os.access(cand, os.X_OK):
            return cand
        found = _shutil.which(cand)
        if found:
            return found
        # an EXPLICIT pin that doesn't resolve must fail, not silently
        # fall back to whatever podman/docker is on PATH (different
        # rootless/network semantics than the operator chose)
        raise RuntimeError(
            f"configured container runtime {cand!r} not found or not "
            "executable")
    for name in ("podman", "docker"):
        found = _shutil.which(name)
        if found:
            return found
    raise RuntimeError(
        "runtime_env requests a container but no container runtime was "
        "found (looked for RAY_TPU_CONTAINER_RUNTIME, podman, docker)")


def wrap_container_cmd(cmd: List[str], env_delta: Dict[str, str],
                       spec: Dict[str, Any], session_dir: str,
                       pythonpath: str,
                       devices: List[str] = ()) -> List[str]:
    """Worker argv -> containerized argv (reference: image_uri.py:106
    _modify_context building the podman invocation).

    Host network (the worker dials the raylet/control on host TCP),
    host /dev/shm (the plasma arena lives there), the session dir and
    every PYTHONPATH entry mounted read-only, env via -e (the runtime
    does not forward its client's environment).  `devices` become
    --device grants — TPU actors get /dev/accel* / vfio nodes."""
    runtime = resolve_container_runtime(spec.get("runtime"))
    args = [runtime, "run", "--rm", "--network=host", "--ipc=host",
            "-v", "/dev/shm:/dev/shm",
            "-v", f"{session_dir}:{session_dir}"]
    for dev in devices:
        args += [f"--device={dev}"]
    for entry in [p for p in pythonpath.split(os.pathsep) if p]:
        args += ["-v", f"{entry}:{entry}:ro"]
    env_delta = dict(env_delta, RAY_TPU_IN_CONTAINER="1")
    for k, v in sorted(env_delta.items()):
        args += ["-e", f"{k}={v}"]
    args += list(spec.get("run_options", ()))
    args.append(spec["image"])
    return args + list(cmd)


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(base):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for f in files:
                full = os.path.join(root, f)
                rel = os.path.relpath(full, base)
                try:
                    zf.write(full, rel)
                except OSError:
                    pass
        if not zf.namelist():
            zf.writestr(".empty", "")
    data = buf.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(f"runtime_env package {path!r} too large "
                         f"({len(data)} > {MAX_PACKAGE_BYTES} bytes)")
    return data


_upload_cache: Dict[Tuple[str, float], str] = {}  # (abspath, max mtime) -> uri


def _tree_mtime(path: str) -> float:
    latest = os.path.getmtime(path)
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
        for f in files:
            try:
                m = os.path.getmtime(os.path.join(root, f))
            except OSError:
                continue
            if m > latest:
                latest = m
    return latest


def _upload_package(control, path: str) -> str:
    """Zip a directory (or take a .zip file) and store it content-addressed
    in the control KV; returns 'pkg:<sha256>'.  Repeat submissions of an
    unchanged tree skip the re-zip via an (abspath, mtime) memo (the
    reference uploads once per job; packaging.py URI cache)."""
    if path.endswith(".zip") and os.path.isfile(path):
        with open(path, "rb") as f:
            data = f.read()
    elif os.path.isdir(path):
        key = (os.path.abspath(path), _tree_mtime(path))
        cached = _upload_cache.get(key)
        if cached is not None:
            return cached
        data = _zip_dir(path)
    else:
        raise ValueError(f"runtime_env path {path!r} is neither a "
                         f"directory nor a .zip file")
    digest = hashlib.sha256(data).hexdigest()
    uri = f"pkg:{digest}"
    if not control.call("kv_exists", {"ns": KV_NS, "key": uri},
                        timeout=30.0):
        control.call("kv_put", {"ns": KV_NS, "key": uri, "val": data},
                     timeout=120.0)
    if os.path.isdir(path):
        _upload_cache[(os.path.abspath(path), _tree_mtime(path))] = uri
    return uri


def prepare(env: Optional[Dict[str, Any]], control) -> Optional[Dict[str, Any]]:
    """Driver-side: validate + upload local dirs, returning a wire-safe
    env whose paths are pkg: URIs (reference: packaging.py upload path)."""
    if not env:
        return None
    env = validate(env)
    out = dict(env)
    wd = env.get("working_dir")
    if wd and not str(wd).startswith("pkg:"):
        out["working_dir"] = _upload_package(control, wd)
    mods = env.get("py_modules")
    if mods:
        out["py_modules"] = [m if str(m).startswith("pkg:")
                             else _upload_package(control, m) for m in mods]
    if env.get("pip") or env.get("uv"):
        # driver policy rides along so the worker installs the same way
        out["_wheelhouse"] = os.environ.get("RAY_TPU_WHEELHOUSE")
    if env.get("uv"):
        out["_uv_bin"] = os.environ.get("RAY_TPU_UV_BIN")
    if env.get("conda"):
        out["_conda_bin"] = os.environ.get("RAY_TPU_CONDA_BIN")
    return out


def _build_target_env(kind: str, digest_material: str,
                      make_cmd) -> str:
    """Shared content-addressed build protocol for pip-style installers:
    digest-keyed dest under the node cache, build into tmp, marker file,
    atomic rename (loser of the race cleans up its tmp).  `make_cmd(tmp)`
    returns the argv installing into tmp."""
    import shutil
    import subprocess

    py = f"py{sys.version_info[0]}.{sys.version_info[1]}"
    digest = hashlib.sha256(
        (digest_material + "\0" + py).encode()).hexdigest()[:20]
    dest = os.path.join(CACHE_ROOT, f"{kind}env-{digest}")
    marker = os.path.join(dest, ".complete")
    if os.path.exists(marker):
        return dest
    tmp = dest + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    cmd = make_cmd(tmp)
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=600)
    except (FileNotFoundError, subprocess.TimeoutExpired) as e:
        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeError(
            f"{kind} runtime_env build failed to run {cmd[0]!r}: "
            f"{e}") from e
    if proc.returncode != 0:
        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeError(
            f"{kind} runtime_env build failed: {proc.stderr[-2000:]}")
    open(os.path.join(tmp, ".complete"), "w").close()
    try:
        os.rename(tmp, dest)
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)  # another worker won
    return dest


def _build_pip_env(requirements: List[str],
                   wheelhouse: Optional[str]) -> str:
    """Build (once per node) a content-addressed package dir for a pip
    requirement list and return it for sys.path insertion (reference:
    runtime_env/pip.py — virtualenv keyed by the requirements hash with a
    node-shared cache).  ``pip install --target`` replaces the venv
    because our workers insert import paths instead of re-exec'ing."""
    reqs = sorted(str(r) for r in requirements)

    def make_cmd(tmp):
        cmd = [sys.executable, "-m", "pip", "install", "--quiet",
               "--target", tmp]
        if wheelhouse:
            # fully offline: wheels (and deps) come from the wheelhouse
            cmd += ["--no-index", "--find-links", wheelhouse]
        return cmd + reqs

    return _build_target_env("pip", "\n".join(reqs), make_cmd)


def _resolve_bin(explicit: Optional[str], env_var: str,
                 name: str) -> Optional[str]:
    """Installer-binary resolution, shared by uv and conda: the driver's
    explicit setting wins WHEN it is an executable on this node (a
    deliberate choice — also how tests inject stubs); a driver-local
    path absent from the worker image falls back to the worker's env
    var, then PATH."""
    import shutil as _shutil

    for cand in (explicit, os.environ.get(env_var), _shutil.which(name)):
        if not cand:
            continue
        if os.path.isfile(cand) and os.access(cand, os.X_OK):
            return cand
        found = _shutil.which(cand)
        if found:
            return found
    return None


def _build_uv_env(requirements: List[str],
                  wheelhouse: Optional[str],
                  uv_bin: Optional[str] = None) -> str:
    """uv-backed requirement install (reference: runtime_env/uv.py):
    same content-addressed target-dir model as pip, but resolved and
    installed by the `uv` binary (_resolve_bin precedence: driver's
    setting when runnable here, else worker env/PATH)."""
    uv = _resolve_bin(uv_bin, "RAY_TPU_UV_BIN", "uv")
    if not uv:
        raise RuntimeError(
            "runtime_env {'uv': ...} requires the `uv` binary on PATH "
            "(or RAY_TPU_UV_BIN); it is not installed in this image — "
            "use {'pip': ...} instead")
    reqs = sorted(str(r) for r in requirements)

    def make_cmd(tmp):
        cmd = [uv, "pip", "install", "--target", tmp,
               "--python", sys.executable]
        if wheelhouse:
            cmd += ["--no-index", "--find-links", wheelhouse]
        return cmd + reqs

    return _build_target_env("uv", "uv\0" + "\n".join(reqs), make_cmd)


def _conda_site_packages(prefix: str) -> str:
    """The env's site-packages dir, version-checked against THIS
    interpreter: our workers import the env in-place (the reference
    re-execs the worker inside the conda env; in-place import only
    works for an ABI-compatible python)."""
    import glob as _glob

    cands = sorted(_glob.glob(os.path.join(prefix, "lib", "python*",
                                           "site-packages")))
    if not cands:
        raise RuntimeError(
            f"conda env at {prefix!r} has no site-packages")
    want = f"python{sys.version_info[0]}.{sys.version_info[1]}"
    for c in cands:
        if want in c:
            return c
    raise RuntimeError(
        f"conda env at {prefix!r} was built for "
        f"{os.path.basename(os.path.dirname(cands[0]))}, but workers run "
        f"{want}: packages would be ABI-incompatible.  Build the env on "
        f"{want} (the reference re-execs workers inside the env; this "
        f"runtime imports it in-place)")


def _build_conda_env(spec, conda_bin: Optional[str] = None) -> str:
    """Conda env support (reference: runtime_env/conda.py).

    spec forms:
      str  — the NAME or PREFIX of an existing conda env (resolved via
             `conda env list`-style prefix paths)
      dict — an environment.yml-style spec, created once per content
             hash with `conda env create`

    Returns the env's site-packages for sys.path insertion (see
    _conda_site_packages for the in-place-import caveat).  The binary
    comes from RAY_TPU_CONDA_BIN or PATH; absent -> loud error."""
    import shutil as _shutil
    import subprocess

    conda = _resolve_bin(conda_bin, "RAY_TPU_CONDA_BIN", "conda")
    if isinstance(spec, str) and os.path.isdir(spec):
        # an existing env PREFIX needs no conda binary at all
        return _conda_site_packages(spec)
    if not conda:
        raise RuntimeError(
            "runtime_env {'conda': ...} requires the `conda` binary on "
            "PATH (or RAY_TPU_CONDA_BIN); it is not installed in this "
            "image — use {'pip': ...} instead")
    if isinstance(spec, str):
        # name of an existing env
        proc = subprocess.run(
            [conda, "env", "list", "--json"],
            capture_output=True, text=True, timeout=120)
        if proc.returncode == 0:
            import json as _json

            for p in _json.loads(proc.stdout).get("envs", []):
                if os.path.basename(p) == spec:
                    return _conda_site_packages(p)
        raise RuntimeError(f"conda env {spec!r} not found")
    # dict spec: create once per content hash — into a tmp prefix with
    # an atomic rename, so concurrent builders (or a crash between
    # create and marker) can never destroy the winner's env
    import json as _json

    blob = _json.dumps(spec, sort_keys=True)
    digest = hashlib.sha256(blob.encode()).hexdigest()[:20]
    prefix = os.path.join(CACHE_ROOT, f"condaenv-{digest}")
    marker = os.path.join(prefix, ".complete")
    if not os.path.exists(marker):
        os.makedirs(CACHE_ROOT, exist_ok=True)
        tmp = prefix + f".tmp{os.getpid()}"
        spec_path = tmp + ".yml"
        with open(spec_path, "w") as f:
            import yaml as _yaml

            _yaml.safe_dump(spec, f)
        try:
            proc = subprocess.run(
                [conda, "env", "create", "--prefix", tmp,
                 "--file", spec_path],
                capture_output=True, text=True, timeout=1800)
        except (FileNotFoundError, subprocess.TimeoutExpired) as e:
            _shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"conda env create failed to run {conda!r}: {e}") from e
        if proc.returncode != 0:
            _shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"conda env create failed: {proc.stderr[-2000:]}")
        open(os.path.join(tmp, ".complete"), "w").close()
        try:
            os.rename(tmp, prefix)
        except OSError:
            _shutil.rmtree(tmp, ignore_errors=True)  # another worker won
    return _conda_site_packages(prefix)


def _fetch_package(control, uri: str) -> str:
    """Worker-side: extract pkg:<hash> into the shared cache; idempotent."""
    with _lock:
        got = _materialized.get(uri)
        if got:
            return got
    dest = os.path.join(CACHE_ROOT, uri.replace(":", "-"))
    marker = os.path.join(dest, ".complete")
    if not os.path.exists(marker):
        data = control.call("kv_get", {"ns": KV_NS, "key": uri},
                            timeout=120.0)
        if data is None:
            raise RuntimeError(f"runtime_env package {uri} missing from KV")
        tmp = dest + f".tmp{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(tmp)
        open(os.path.join(tmp, ".complete"), "w").close()
        try:
            os.rename(tmp, dest)
        except OSError:
            # another worker won the race
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    with _lock:
        _materialized[uri] = dest
    return dest


class Context:
    """Materialized environment, applied around execution."""

    def __init__(self, env_vars: Dict[str, str], sys_paths: List[str],
                 cwd: Optional[str]):
        self.env_vars = env_vars
        self.sys_paths = sys_paths
        self.cwd = cwd
        self._saved_env: Dict[str, Optional[str]] = {}
        self._saved_cwd: Optional[str] = None
        self._inserted_paths: List[str] = []

    def __enter__(self):
        for k, v in self.env_vars.items():
            self._saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        for p in reversed(self.sys_paths):
            if p not in sys.path:
                sys.path.insert(0, p)
                self._inserted_paths.append(p)
        if self.cwd:
            self._saved_cwd = os.getcwd()
            os.chdir(self.cwd)
        return self

    def __exit__(self, *exc):
        for k, old in self._saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        self._saved_env.clear()
        # drop our sys.path entries AND the modules imported from them so
        # a reused worker's later tasks don't see this env's packages
        # (sys.modules would otherwise cache them past the path removal)
        for p in self._inserted_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if self._inserted_paths:
            prefixes = tuple(p + os.sep for p in self._inserted_paths)
            for mod_name, mod in list(sys.modules.items()):
                f = getattr(mod, "__file__", None)
                if f and f.startswith(prefixes):
                    del sys.modules[mod_name]
        self._inserted_paths.clear()
        if self._saved_cwd:
            try:
                os.chdir(self._saved_cwd)
            except OSError:
                pass
            self._saved_cwd = None
        return False

    def apply_permanent(self):
        """For actor processes: the env applies for the process lifetime."""
        self.__enter__()


def materialize(env: Optional[Dict[str, Any]], control) -> Context:
    """Worker-side: resolve pkg URIs and build an applicable Context
    (reference: the runtime_env agent's CreateRuntimeEnv)."""
    env = env or {}
    if env.get("container") and not os.environ.get("RAY_TPU_IN_CONTAINER"):
        # containers wrap the WORKER LAUNCH (raylet-side, actors get a
        # dedicated wrapped worker); an in-process materialize cannot
        # retrofit one — reject loudly instead of running outside the
        # requested image
        raise RuntimeError(
            "container runtime_env reached a non-containerized worker: "
            "containers are applied at worker spawn and currently "
            "supported for ACTORS (which get a dedicated worker); plain "
            "tasks run on pooled workers — wrap the work in an actor")
    sys_paths: List[str] = []
    cwd = None
    wd = env.get("working_dir")
    if wd:
        cwd = _fetch_package(control, wd) if str(wd).startswith("pkg:") \
            else str(wd)
        sys_paths.append(cwd)
    for m in env.get("py_modules") or []:
        p = _fetch_package(control, m) if str(m).startswith("pkg:") else str(m)
        sys_paths.append(p)
    pip_reqs = env.get("pip")
    if pip_reqs:
        if isinstance(pip_reqs, dict):  # reference: {"packages": [...]}
            pip_reqs = pip_reqs.get("packages") or []
        sys_paths.append(_build_pip_env(list(pip_reqs),
                                        env.get("_wheelhouse")))
    uv_reqs = env.get("uv")
    if uv_reqs:
        if isinstance(uv_reqs, dict):  # reference: {"packages": [...]}
            uv_reqs = uv_reqs.get("packages") or []
        sys_paths.append(_build_uv_env(list(uv_reqs),
                                       env.get("_wheelhouse"),
                                       env.get("_uv_bin")))
    if env.get("conda"):
        sys_paths.append(_build_conda_env(env["conda"],
                                          env.get("_conda_bin")))
    return Context(dict(env.get("env_vars") or {}), sys_paths, cwd)
