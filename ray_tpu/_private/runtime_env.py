"""Runtime environments: per-task/actor/job execution contexts.

Analog of the reference's runtime_env machinery (reference:
python/ray/_private/runtime_env/ — working_dir.py, py_modules.py,
packaging.py URI cache, plugin.py; agent materializes envs per node).
TPU-native simplifications: packages travel through the control-plane KV
(content-addressed zips) instead of a dedicated agent protocol, and
materialization happens lazily in the worker with a node-shared
content-addressed cache.

Supported fields:
  env_vars     {str: str}   applied around execution
  working_dir  path/zip     shipped, extracted, becomes cwd + sys.path[0]
  py_modules   [paths]      shipped, extracted, prepended to sys.path
  pip          [requirements]  content-addressed package env built once
               per node (pip install --target into the shared cache) and
               prepended to sys.path — the venv-equivalent for in-process
               workers (reference: runtime_env/pip.py builds a virtualenv
               and spawns the worker inside it; our workers already run,
               so the env is import-path scoped instead).  Gated: rejected
               unless RAY_TPU_ALLOW_PKG_INSTALL=1.  With
               RAY_TPU_WHEELHOUSE=<dir> the install is fully offline
               (--no-index --find-links), which is also how it is tested.
  conda        rejected unless RAY_TPU_ALLOW_PKG_INSTALL=1 (the build
               forbids network installs; the hook exists for parity)
"""

from __future__ import annotations

import hashlib
import io
import logging
import os
import sys
import threading
import zipfile
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

KV_NS = "runtime_env_packages"
CACHE_ROOT = os.environ.get("RAY_TPU_RTENV_CACHE",
                            "/dev/shm/ray_tpu/rtenv-cache")
from .config import cfg as _cfg

MAX_PACKAGE_BYTES = _cfg().rtenv_max_bytes
_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules"}

_lock = threading.Lock()
_materialized: Dict[str, str] = {}  # pkg hash -> extracted dir


def validate(env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    env = dict(env or {})
    unknown = set(env) - {"env_vars", "working_dir", "py_modules", "pip",
                          "conda", "config"}
    if unknown:
        raise ValueError(f"unsupported runtime_env fields: {sorted(unknown)}")
    if env.get("pip") or env.get("conda"):
        if not _cfg().allow_pkg_install:
            raise ValueError(
                "runtime_env pip/conda installs are disabled in this "
                "deployment (set RAY_TPU_ALLOW_PKG_INSTALL=1 to enable)")
    ev = env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise ValueError("env_vars must be {str: str}")
    return env


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(base):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for f in files:
                full = os.path.join(root, f)
                rel = os.path.relpath(full, base)
                try:
                    zf.write(full, rel)
                except OSError:
                    pass
        if not zf.namelist():
            zf.writestr(".empty", "")
    data = buf.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(f"runtime_env package {path!r} too large "
                         f"({len(data)} > {MAX_PACKAGE_BYTES} bytes)")
    return data


_upload_cache: Dict[Tuple[str, float], str] = {}  # (abspath, max mtime) -> uri


def _tree_mtime(path: str) -> float:
    latest = os.path.getmtime(path)
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
        for f in files:
            try:
                m = os.path.getmtime(os.path.join(root, f))
            except OSError:
                continue
            if m > latest:
                latest = m
    return latest


def _upload_package(control, path: str) -> str:
    """Zip a directory (or take a .zip file) and store it content-addressed
    in the control KV; returns 'pkg:<sha256>'.  Repeat submissions of an
    unchanged tree skip the re-zip via an (abspath, mtime) memo (the
    reference uploads once per job; packaging.py URI cache)."""
    if path.endswith(".zip") and os.path.isfile(path):
        with open(path, "rb") as f:
            data = f.read()
    elif os.path.isdir(path):
        key = (os.path.abspath(path), _tree_mtime(path))
        cached = _upload_cache.get(key)
        if cached is not None:
            return cached
        data = _zip_dir(path)
    else:
        raise ValueError(f"runtime_env path {path!r} is neither a "
                         f"directory nor a .zip file")
    digest = hashlib.sha256(data).hexdigest()
    uri = f"pkg:{digest}"
    if not control.call("kv_exists", {"ns": KV_NS, "key": uri},
                        timeout=30.0):
        control.call("kv_put", {"ns": KV_NS, "key": uri, "val": data},
                     timeout=120.0)
    if os.path.isdir(path):
        _upload_cache[(os.path.abspath(path), _tree_mtime(path))] = uri
    return uri


def prepare(env: Optional[Dict[str, Any]], control) -> Optional[Dict[str, Any]]:
    """Driver-side: validate + upload local dirs, returning a wire-safe
    env whose paths are pkg: URIs (reference: packaging.py upload path)."""
    if not env:
        return None
    env = validate(env)
    out = dict(env)
    wd = env.get("working_dir")
    if wd and not str(wd).startswith("pkg:"):
        out["working_dir"] = _upload_package(control, wd)
    mods = env.get("py_modules")
    if mods:
        out["py_modules"] = [m if str(m).startswith("pkg:")
                             else _upload_package(control, m) for m in mods]
    if env.get("pip"):
        # driver policy rides along so the worker installs the same way
        out["_wheelhouse"] = os.environ.get("RAY_TPU_WHEELHOUSE")
    return out


def _build_pip_env(requirements: List[str],
                   wheelhouse: Optional[str]) -> str:
    """Build (once per node) a content-addressed package dir for a pip
    requirement list and return it for sys.path insertion (reference:
    runtime_env/pip.py — virtualenv keyed by the requirements hash with a
    node-shared cache).  ``pip install --target`` replaces the venv
    because our workers insert import paths instead of re-exec'ing."""
    import subprocess

    reqs = sorted(str(r) for r in requirements)
    py = f"py{sys.version_info[0]}.{sys.version_info[1]}"
    digest = hashlib.sha256(
        ("\n".join(reqs) + "\0" + py).encode()).hexdigest()[:20]
    dest = os.path.join(CACHE_ROOT, f"pipenv-{digest}")
    marker = os.path.join(dest, ".complete")
    if os.path.exists(marker):
        return dest
    tmp = dest + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    cmd = [sys.executable, "-m", "pip", "install", "--quiet",
           "--target", tmp]
    if wheelhouse:
        # fully offline: wheels (and their deps) come from the wheelhouse
        cmd += ["--no-index", "--find-links", wheelhouse]
    cmd += reqs
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeError(
            f"pip runtime_env build failed: {proc.stderr[-2000:]}")
    open(os.path.join(tmp, ".complete"), "w").close()
    try:
        os.rename(tmp, dest)
    except OSError:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)  # another worker won
    return dest


def _fetch_package(control, uri: str) -> str:
    """Worker-side: extract pkg:<hash> into the shared cache; idempotent."""
    with _lock:
        got = _materialized.get(uri)
        if got:
            return got
    dest = os.path.join(CACHE_ROOT, uri.replace(":", "-"))
    marker = os.path.join(dest, ".complete")
    if not os.path.exists(marker):
        data = control.call("kv_get", {"ns": KV_NS, "key": uri},
                            timeout=120.0)
        if data is None:
            raise RuntimeError(f"runtime_env package {uri} missing from KV")
        tmp = dest + f".tmp{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(tmp)
        open(os.path.join(tmp, ".complete"), "w").close()
        try:
            os.rename(tmp, dest)
        except OSError:
            # another worker won the race
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    with _lock:
        _materialized[uri] = dest
    return dest


class Context:
    """Materialized environment, applied around execution."""

    def __init__(self, env_vars: Dict[str, str], sys_paths: List[str],
                 cwd: Optional[str]):
        self.env_vars = env_vars
        self.sys_paths = sys_paths
        self.cwd = cwd
        self._saved_env: Dict[str, Optional[str]] = {}
        self._saved_cwd: Optional[str] = None
        self._inserted_paths: List[str] = []

    def __enter__(self):
        for k, v in self.env_vars.items():
            self._saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        for p in reversed(self.sys_paths):
            if p not in sys.path:
                sys.path.insert(0, p)
                self._inserted_paths.append(p)
        if self.cwd:
            self._saved_cwd = os.getcwd()
            os.chdir(self.cwd)
        return self

    def __exit__(self, *exc):
        for k, old in self._saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        self._saved_env.clear()
        # drop our sys.path entries AND the modules imported from them so
        # a reused worker's later tasks don't see this env's packages
        # (sys.modules would otherwise cache them past the path removal)
        for p in self._inserted_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if self._inserted_paths:
            prefixes = tuple(p + os.sep for p in self._inserted_paths)
            for mod_name, mod in list(sys.modules.items()):
                f = getattr(mod, "__file__", None)
                if f and f.startswith(prefixes):
                    del sys.modules[mod_name]
        self._inserted_paths.clear()
        if self._saved_cwd:
            try:
                os.chdir(self._saved_cwd)
            except OSError:
                pass
            self._saved_cwd = None
        return False

    def apply_permanent(self):
        """For actor processes: the env applies for the process lifetime."""
        self.__enter__()


def materialize(env: Optional[Dict[str, Any]], control) -> Context:
    """Worker-side: resolve pkg URIs and build an applicable Context
    (reference: the runtime_env agent's CreateRuntimeEnv)."""
    env = env or {}
    sys_paths: List[str] = []
    cwd = None
    wd = env.get("working_dir")
    if wd:
        cwd = _fetch_package(control, wd) if str(wd).startswith("pkg:") \
            else str(wd)
        sys_paths.append(cwd)
    for m in env.get("py_modules") or []:
        p = _fetch_package(control, m) if str(m).startswith("pkg:") else str(m)
        sys_paths.append(p)
    pip_reqs = env.get("pip")
    if pip_reqs:
        if isinstance(pip_reqs, dict):  # reference: {"packages": [...]}
            pip_reqs = pip_reqs.get("packages") or []
        sys_paths.append(_build_pip_env(list(pip_reqs),
                                        env.get("_wheelhouse")))
    return Context(dict(env.get("env_vars") or {}), sys_paths, cwd)
