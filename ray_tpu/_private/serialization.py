"""Serialization: pickle-5 out-of-band buffers + device-array awareness.

Equivalent of the reference's serialization layer (cloudpickle + zero-copy
numpy via plasma buffers, reference: python/ray/_private/serialization.py).
TPU-native twist: `jax.Array` values are first-class.  Inside one process they
stay device-resident in the in-process store; when they must cross a process
boundary through the object plane they are staged to host (device_get) and the
sharding is recorded so the receiver can restore placement.  Large device-to-
device movement should use the collective plane (compiled ICI collectives),
not the object store — this path is correctness, not the fast path.

ObjectRefs inside values are swapped for SerializedRef markers; the
deserializing side re-wraps them via a context hook so borrower ref-counting
works (reference: reference_count.h borrower protocol).
"""

from __future__ import annotations

import io
import pickle
import sys
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle

from .common import SerializedRef

# Hooks installed by core.py: map ObjectRef -> SerializedRef and back.
_ref_to_marker: Optional[Callable[[Any], Any]] = None
_marker_to_ref: Optional[Callable[[SerializedRef], Any]] = None
_ref_type: Optional[type] = None


def install_ref_hooks(ref_type: type, to_marker, from_marker) -> None:
    global _ref_type, _ref_to_marker, _marker_to_ref
    _ref_type = ref_type
    _ref_to_marker = to_marker
    _marker_to_ref = from_marker


def _jax_types():
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    return jax


class _DeviceArrayStandIn:
    """Host-staged stand-in for a jax.Array crossing the object plane."""

    def __init__(self, np_value, sharding_desc):
        self.np_value = np_value
        # portable descriptor: {"spec": nested PartitionSpec entries}
        # (older pickles carry a str(sharding); treated as no descriptor)
        self.sharding_desc = sharding_desc


def _pspec_entries(spec) -> Optional[list]:
    """PartitionSpec -> JSON-ish nested lists (axis name, tuple of names,
    or None per dim); None when any entry is not mesh-axis-shaped."""
    out = []
    for e in tuple(spec):
        if e is None or isinstance(e, str):
            out.append(e)
        elif isinstance(e, (tuple, list)) and \
                all(isinstance(a, str) for a in e):
            out.append(list(e))
        else:
            return None
    return out


def _restore_device_array(stand_in: _DeviceArrayStandIn):
    jax = _jax_types()
    if jax is None:
        return stand_in.np_value
    desc = stand_in.sharding_desc
    if isinstance(desc, dict) and desc.get("spec") is not None:
        # re-place onto the receiving process's declared mesh when its
        # axes cover the spec (mesh geometry is process-local, so the
        # sender's mesh object itself can never travel)
        from ray_tpu.parallel import get_default_mesh

        mesh = get_default_mesh()
        if mesh is not None:
            entries = [tuple(e) if isinstance(e, list) else e
                       for e in desc["spec"]]
            used = {a for e in entries
                    for a in (e if isinstance(e, tuple)
                              else (e,) if e else ())}
            if used <= set(mesh.axis_names):
                try:
                    return jax.device_put(
                        stand_in.np_value,
                        jax.sharding.NamedSharding(
                            mesh, jax.sharding.PartitionSpec(*entries)))
                except Exception:
                    pass  # shape indivisible on this mesh: fall through
    # no declared mesh (or incompatible): default device placement;
    # callers that need a specific sharding re-place explicitly
    return jax.numpy.asarray(stand_in.np_value)


class _Pickler(cloudpickle.Pickler):
    def __init__(self, file, buffer_callback=None):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)

    def reducer_override(self, obj):
        if _ref_type is not None and type(obj) is _ref_type:
            return (_deserialize_marker, (_ref_to_marker(obj),))
        jax = _jax_types()
        if jax is not None and isinstance(obj, jax.Array):
            import numpy as np

            desc = None
            try:
                sh = obj.sharding
                if isinstance(sh, jax.sharding.NamedSharding):
                    entries = _pspec_entries(sh.spec)
                    if entries is not None:
                        desc = {"spec": entries}
            except Exception:
                desc = None
            host = np.asarray(obj)
            return (_restore_device_array, (_DeviceArrayStandIn(host, desc),))
        # delegate to cloudpickle's own override (functions/classes by value)
        return super().reducer_override(obj)


def _deserialize_marker(marker: SerializedRef):
    if _marker_to_ref is None:
        return marker
    return _marker_to_ref(marker)


# Exact-type primitives can never hit reducer_override (no ObjectRef
# markers, no device arrays, no closures) — plain pickle is safe and
# skips a CloudPickler construction per value on the task hot path.
_PRIMITIVE_TYPES = frozenset({type(None), bool, int, float, str, bytes})


def _is_primitive(value: Any) -> bool:
    t = type(value)
    if t in _PRIMITIVE_TYPES:
        return True
    if t is tuple or t is list:
        return len(value) <= 8 and \
            all(type(v) in _PRIMITIVE_TYPES for v in value)
    return False


def dumps_oob(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Serialize with out-of-band buffers (zero-copy for numpy/bytes)."""
    if _is_primitive(value):
        return pickle.dumps(value, protocol=5), []
    buffers: List[pickle.PickleBuffer] = []
    f = io.BytesIO()
    p = _Pickler(f, buffer_callback=buffers.append)
    p.dump(value)
    return f.getvalue(), buffers


def loads_oob(meta: bytes, buffers: List[memoryview]) -> Any:
    return pickle.loads(meta, buffers=buffers)


def dumps_inline(value: Any) -> bytes:
    """Serialize fully in-band (for RPC messages)."""
    if _is_primitive(value):
        return pickle.dumps(value, protocol=5)
    f = io.BytesIO()
    _Pickler(f).dump(value)
    return f.getvalue()


def loads_inline(blob: bytes) -> Any:
    return pickle.loads(blob)


def value_nbytes_estimate(meta: bytes, buffers) -> int:
    return len(meta) + sum(len(b.raw() if hasattr(b, "raw") else b) for b in buffers)
