"""Serialization: pickle-5 out-of-band buffers + device-array awareness.

Equivalent of the reference's serialization layer (cloudpickle + zero-copy
numpy via plasma buffers, reference: python/ray/_private/serialization.py).
TPU-native twist: `jax.Array` values are first-class.  Inside one process they
stay device-resident in the in-process store; when they must cross a process
boundary through the object plane they are staged to host (device_get) and the
sharding is recorded so the receiver can restore placement.  Large device-to-
device movement should use the collective plane (compiled ICI collectives),
not the object store — this path is correctness, not the fast path.

ObjectRefs inside values are swapped for SerializedRef markers; the
deserializing side re-wraps them via a context hook so borrower ref-counting
works (reference: reference_count.h borrower protocol).
"""

from __future__ import annotations

import io
import pickle
import sys
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle

from .common import SerializedRef

# Hooks installed by core.py: map ObjectRef -> SerializedRef and back.
_ref_to_marker: Optional[Callable[[Any], Any]] = None
_marker_to_ref: Optional[Callable[[SerializedRef], Any]] = None
_ref_type: Optional[type] = None


def install_ref_hooks(ref_type: type, to_marker, from_marker) -> None:
    global _ref_type, _ref_to_marker, _marker_to_ref
    _ref_type = ref_type
    _ref_to_marker = to_marker
    _marker_to_ref = from_marker


def _jax_types():
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    return jax


class _DeviceArrayStandIn:
    """Host-staged stand-in for a jax.Array crossing the object plane."""

    def __init__(self, np_value, sharding_desc):
        self.np_value = np_value
        # portable descriptor: {"spec": nested PartitionSpec entries}
        # (older pickles carry a str(sharding); treated as no descriptor)
        self.sharding_desc = sharding_desc


def _pspec_entries(spec) -> Optional[list]:
    """PartitionSpec -> JSON-ish nested lists (axis name, tuple of names,
    or None per dim); None when any entry is not mesh-axis-shaped."""
    out = []
    for e in tuple(spec):
        if e is None or isinstance(e, str):
            out.append(e)
        elif isinstance(e, (tuple, list)) and \
                all(isinstance(a, str) for a in e):
            out.append(list(e))
        else:
            return None
    return out


def _restore_device_array(stand_in: _DeviceArrayStandIn):
    jax = _jax_types()
    if jax is None:
        return stand_in.np_value
    desc = stand_in.sharding_desc
    if isinstance(desc, dict) and desc.get("spec") is not None:
        # re-place onto the receiving process's declared mesh when its
        # axes cover the spec (mesh geometry is process-local, so the
        # sender's mesh object itself can never travel)
        from ray_tpu.parallel import get_default_mesh

        mesh = get_default_mesh()
        if mesh is not None:
            entries = [tuple(e) if isinstance(e, list) else e
                       for e in desc["spec"]]
            used = {a for e in entries
                    for a in (e if isinstance(e, tuple)
                              else (e,) if e else ())}
            if used <= set(mesh.axis_names):
                try:
                    return jax.device_put(
                        stand_in.np_value,
                        jax.sharding.NamedSharding(
                            mesh, jax.sharding.PartitionSpec(*entries)))
                except Exception:
                    pass  # shape indivisible on this mesh: fall through
    # no declared mesh (or incompatible): default device placement;
    # callers that need a specific sharding re-place explicitly
    return jax.numpy.asarray(stand_in.np_value)


class _Pickler(cloudpickle.Pickler):
    def __init__(self, file, buffer_callback=None):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)

    def reducer_override(self, obj):
        if _ref_type is not None and type(obj) is _ref_type:
            return (_deserialize_marker, (_ref_to_marker(obj),))
        jax = _jax_types()
        if jax is not None and isinstance(obj, jax.Array):
            import numpy as np

            desc = None
            try:
                sh = obj.sharding
                if isinstance(sh, jax.sharding.NamedSharding):
                    entries = _pspec_entries(sh.spec)
                    if entries is not None:
                        desc = {"spec": entries}
            except Exception:
                desc = None
            host = np.asarray(obj)
            return (_restore_device_array, (_DeviceArrayStandIn(host, desc),))
        # delegate to cloudpickle's own override (functions/classes by value)
        return super().reducer_override(obj)


def _deserialize_marker(marker: SerializedRef):
    if _marker_to_ref is None:
        return marker
    return _marker_to_ref(marker)


# Exact-type primitives can never hit reducer_override (no ObjectRef
# markers, no device arrays, no closures) — plain pickle is safe and
# skips a CloudPickler construction per value on the task hot path.
_PRIMITIVE_TYPES = frozenset({type(None), bool, int, float, str, bytes})


def _is_primitive(value: Any) -> bool:
    t = type(value)
    if t in _PRIMITIVE_TYPES:
        return True
    if t is tuple or t is list:
        return len(value) <= 8 and \
            all(type(v) in _PRIMITIVE_TYPES for v in value)
    return False


def dumps_oob(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Serialize with out-of-band buffers (zero-copy for numpy/bytes)."""
    if _is_primitive(value):
        return pickle.dumps(value, protocol=5), []
    buffers: List[pickle.PickleBuffer] = []
    f = io.BytesIO()
    p = _Pickler(f, buffer_callback=buffers.append)
    p.dump(value)
    return f.getvalue(), buffers


def loads_oob(meta: bytes, buffers: List[memoryview]) -> Any:
    return pickle.loads(meta, buffers=buffers)


def dumps_inline(value: Any) -> bytes:
    """Serialize fully in-band (for RPC messages)."""
    if _is_primitive(value):
        return pickle.dumps(value, protocol=5)
    f = io.BytesIO()
    _Pickler(f).dump(value)
    return f.getvalue()


# -- small-arg fast path ------------------------------------------------------
#
# The task hot path serializes (args, kwargs) once per .remote().  When the
# args are a short tuple of plain scalars/bytes/ObjectRefs with no kwargs
# (the benchmark and RL actor-step shape), full pickle framing through
# _Pickler is pure overhead: a plain protocol-5 pickle of the converted
# tuple suffices, and repeated identical ref-free tuples can reuse their
# bytes outright.  Blobs carry a one-byte prefix that no pickle stream
# starts with (protocol-5 pickles begin with b'\x80'), so loads_inline
# stays a single entry point for both framings.

_SMALL_PREFIX = b"\xf5"
_SMALL_MAX_ARGS = 8

# type-aware memo: hash(1) == hash(True) == hash(1.0) and they compare
# equal, but their pickles differ — the key must carry the value types.
# Only ref-free tuples are memoizable (ref->marker conversion pins the
# object per serialization; reusing a blob must not skip that bookkeeping).
_small_memo: dict = {}


def _small_memo_key(args: tuple):
    try:
        return tuple((type(a), a) for a in args)
    except TypeError:  # pragma: no cover - all eligible types are hashable
        return None


def dumps_args_small(args: tuple, *, limit: int,
                     memo_cap: int = 0) -> Optional[bytes]:
    """Fast inline framing for a no-kwargs call whose args are all plain
    scalars/bytes or ObjectRefs.  Returns None when ineligible (caller
    falls back to the full path); round-trips through loads_inline to the
    same (args, {}) the full path produces."""
    if limit <= 0 or len(args) > _SMALL_MAX_ARGS:
        return None
    has_ref = False
    for a in args:
        t = type(a)
        if t in _PRIMITIVE_TYPES:
            # big strings/bytes would pickle past the limit anyway;
            # bail before paying for the dump on every call
            if (t is bytes or t is str) and len(a) > limit:
                return None
            continue
        if _ref_type is not None and t is _ref_type:
            has_ref = True
            continue
        return None
    if not has_ref and memo_cap > 0:
        key = _small_memo_key(args)
        cached = _small_memo.get(key) if key is not None else None
        if cached is not None:
            return cached
    else:
        key = None
    if has_ref:
        # swap refs for markers by hand — plain pickle can't carry
        # ObjectRefs (their __reduce__ raises), and the conversion's pin
        # bookkeeping must run exactly like the full path's
        ref_pos = []
        conv = []
        for i, a in enumerate(args):
            if type(a) is _ref_type:
                ref_pos.append(i)
                conv.append(_ref_to_marker(a))
            else:
                conv.append(a)
        blob = _SMALL_PREFIX + pickle.dumps(
            (tuple(conv), tuple(ref_pos)), protocol=5)
    else:
        blob = _SMALL_PREFIX + pickle.dumps((args, ()), protocol=5)
    if len(blob) > limit:
        return None
    if key is not None:
        if len(_small_memo) >= memo_cap:
            _small_memo.clear()  # cheap bound; the hot set refills fast
        _small_memo[key] = blob
    return blob


def _loads_args_small(blob: bytes):
    conv, ref_pos = pickle.loads(blob[1:])
    if ref_pos:
        out = list(conv)
        for i in ref_pos:
            out[i] = _marker_to_ref(out[i]) if _marker_to_ref is not None \
                else out[i]
        return tuple(out), {}
    return conv, {}


def loads_inline(blob: bytes) -> Any:
    if blob[:1] == _SMALL_PREFIX:
        return _loads_args_small(blob)
    return pickle.loads(blob)


def value_nbytes_estimate(meta: bytes, buffers) -> int:
    return len(meta) + sum(len(b.raw() if hasattr(b, "raw") else b) for b in buffers)
