"""Central typed flag table.

Analog of the reference's ``RAY_CONFIG`` system (reference:
src/ray/common/ray_config_def.h — 218 typed flags, each overridable via
a ``RAY_<name>`` env var or the ``_system_config`` JSON handed to every
process).  Here: a declarative table of (name, type, default, help); the
resolved value for flag NAME comes from, in priority order,

  1. the ``RAY_TPU_<NAME>`` environment variable,
  2. the system-config JSON in ``RAY_TPU_SYSTEM_CONFIG`` (set by
     ``ray_tpu.init(_system_config=...)`` and propagated by the
     bootstrapper into every daemon it spawns),
  3. the table default.

Usage::

    from ray_tpu._private.config import cfg
    timeout = cfg().node_death_timeout_s
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

# (name, type, default, help) — name doubles as the env suffix
CONFIG_DEFS: List[Tuple[str, type, Any, str]] = [
    # -- control plane / failure detection
    ("heartbeat_interval_s", float, 0.5,
     "raylet -> control heartbeat period"),
    ("resource_sync_delta", bool, True,
     "ship node availability only when it changed (versioned delta "
     "sync, the ray_syncer analog); False = full snapshot every beat"),
    ("node_death_timeout_s", float, 10.0,
     "missed-heartbeat window before a node is declared dead"),
    ("control_reconnect_s", float, 20.0,
     "how long clients retry re-attaching to a restarted control plane"),
    ("preemption_poll_s", float, 1.0,
     "raylet poll period of the preemption/maintenance-event source "
     "(RAY_TPU_PREEMPTION_FILE sentinel or the GCE metadata endpoint)"),
    ("drain_grace_s", float, 30.0,
     "advisory deadline attached to a node drain notice that carries "
     "no explicit grace window"),
    ("preemption_debounce_s", float, 5.0,
     "flap suppression window: a preemption notice edge within this "
     "many seconds of the last fired notice is swallowed (drain -> "
     "cancel -> drain inside one window costs one drain report, not "
     "two); 0 disables"),
    ("rpc_backoff_base_s", float, 0.05,
     "initial delay of the jittered-exponential backoff used by RPC "
     "reconnect/retry loops (raylet re-home, driver control rebuild, "
     "idempotent lease replay)"),
    ("rpc_backoff_cap_s", float, 2.0,
     "ceiling of the jittered-exponential RPC reconnect/retry backoff"),
    ("restore_owner_grace_s", float, 60.0,
     "window for a driver job to re-register after a control restart "
     "before its restored non-detached actors are reaped"),
    ("actor_adopt_grace_s", float, 15.0,
     "window after a control restart/failover for raylets to re-home "
     "and adopt their still-running actor workers in place before the "
     "control plane falls back to rescheduling them fresh"),
    # -- task submission (NOTE: bound at module import in the driver's
    # own process — set via env or _system_config before daemons spawn)
    ("pipeline_depth", int, 8,
     "tasks pushed per leased worker before waiting on replies"),
    ("submit_batch", int, 64,
     "max TaskSpecs coalesced into one framed push_tasks RPC per leased "
     "worker; 1 = escape hatch, bypasses the combining flusher and ships "
     "one spec per frame (bit-identical semantics, no coalescing)"),
    ("submit_mux", bool, True,
     "multi-client submit multiplexer: when a raylet sees >=2 concurrent "
     "driver processes it relays their eligible plain tasks itself (one "
     "framed stream per driver, no per-driver lease conversations); "
     "0 = escape hatch, every driver keeps its own lease protocol"),
    ("lease_grant_batch", int, 16,
     "max leases requested from the raylet in one request_leases RPC "
     "(the vectorized ramp-up; 1 degrades to the old one-lease-per-"
     "round-trip behavior)"),
    ("pending_lease_cap", int, 64,
     "max outstanding lease requests per scheduling pool (bounds the "
     "one-request-per-queued-task aim during 100k-task bursts)"),
    ("small_arg_limit", int, 4096,
     "max serialized bytes for the small-arg inline fast path (plain "
     "scalars/bytes/ObjectRefs skip full pickle framing); 0 disables"),
    ("small_arg_memo", int, 512,
     "entries kept in the small-arg serialization memo (repeated "
     "identical ref-free arg tuples reuse their bytes); 0 disables"),
    ("idle_lease_ttl_s", float, 1.0,
     "idle time before a lease is returned to the raylet"),
    ("delete_grace_s", float, 0.5,
     "delay before a released object is reclaimed"),
    ("inline_object_limit", int, 100 * 1024,
     "max bytes for values carried inline instead of via the shm store"),
    # -- object store / spilling
    ("object_store_bytes", int, 0,
     "shm arena capacity per node (0 = auto-size)"),
    ("object_spilling", bool, True,
     "spill primary copies to disk under memory pressure"),
    ("spill_high", float, 0.8,
     "store fullness fraction that triggers spilling"),
    ("spill_low", float, 0.6,
     "store fullness fraction spilling drains down to"),
    ("memory_monitor_refresh_ms", int, 250,
     "OOM watchdog poll period"),
    # -- workers
    ("worker_prestart", int, 4,
     "warm workers each raylet keeps ready (capped to the CPU slots)"),
    ("native_sched", bool, True,
     "use the native C++ scheduling policy engine"),
    ("task_events", bool, True,
     "export task lifecycle events to the control plane"),
    ("max_task_events", int, 10000,
     "task events retained by the control plane"),
    ("max_dead_actors", int, 10000,
     "destroyed actor records kept for introspection (reference: "
     "maximum_gcs_destroyed_actor_cached_count)"),
    ("max_cluster_events", int, 10000,
     "structured cluster events retained by the control plane "
     "(node/actor/pg/job lifecycle; separate from task events so "
     "tuning one buffer never evicts the other's history)"),
    # -- distributed tracing
    ("trace_sample", float, 0.0,
     "head-based trace sampling ratio in [0,1]: >0 auto-enables "
     "tracing and samples that fraction of new traces (deterministic "
     "on trace_id, so every process agrees); 0 leaves the sampler off "
     "— tracing enabled explicitly via a startup hook records all"),
    ("trace_buffer_cap", int, 4096,
     "finished spans buffered per process before drop-oldest (the "
     "span buffer flushing batched report_spans to the control plane)"),
    ("trace_flush_interval_s", float, 0.5,
     "span-buffer flush period (rate limit on report_spans pushes)"),
    ("trace_store_cap", int, 512,
     "traces retained by the control plane's span collector (LRU "
     "eviction beyond this)"),
    ("trace_store_ttl_s", float, 600.0,
     "idle TTL before a collected trace is evicted from the control "
     "plane's _tracing KV namespace"),
    ("trace_spans_per_trace", int, 512,
     "max spans stored per trace (overflow counted, not stored)"),
    # -- runtime env
    ("rtenv_max_bytes", int, 256 * 1024 * 1024,
     "max size of one runtime_env package"),
    ("allow_pkg_install", bool, False,
     "allow runtime_env pip/conda materialization"),
    # -- collectives
    ("collective_compression", str, "",
     "default compression for collective ops: '' = off, or a spec like "
     "'int8' / 'int8:block=512,stochastic=1,ef=0' (block-wise quantized "
     "allreduce; see collective/compression.py).  Per-call compression= "
     "and the Train backend's CompressionConfig override this"),
    # -- serving (the LLM engine knobs live here, not as hardcoded
    # constants in serve/llm.py, so one RAY_TPU_SERVE_* env var reaches
    # every replica the bootstrapper spawns)
    ("serve_engine", str, "paged",
     "LLM decode engine: 'paged' (continuous batching over the paged "
     "KV arena), 'contiguous' (continuous batching over per-slot "
     "contiguous caches; the parity baseline), or 'static' (legacy "
     "serve.batch micro-batching)"),
    ("serve_gen_cache_cap", int, 8,
     "compiled-program LRU entries per LLM replica (generate/prefill/"
     "stream-step variants; the engine's own step programs are bounded "
     "by construction and not counted)"),
    ("serve_max_slots", int, 8,
     "decode slots per replica = the fixed batch width of the compiled "
     "continuous-batching step program"),
    ("serve_page_size", int, 16,
     "KV-cache page size in token positions"),
    ("serve_num_pages", int, 0,
     "pages in the device KV arena (incl. the reserved null page); "
     "0 = auto-size so every slot can hold a full-length sequence"),
    ("serve_max_total", int, 0,
     "max prompt+generation positions per sequence; 0 = the model's "
     "max_seq"),
    ("serve_queue_cap", int, 32,
     "waiting-queue length at which the engine rejects new requests "
     "(AdmissionRejected -> HTTP 503 + Retry-After)"),
    ("serve_shed_queue_depth", int, 16,
     "queue depth at which the replica advertises accepting=False so "
     "the router sheds before the hard queue_cap bounces requests"),
    ("serve_retry_after_s", float, 1.0,
     "Retry-After hint attached to shed/rejected serve requests"),
    ("serve_prefill_bucket", int, 32,
     "prefill token chunks are padded to multiples of this (bounds "
     "prefill compile variants to max_total/bucket)"),
    ("serve_replay_budget", int, 2,
     "replays per request after a replica dies mid-call (actor-died / "
     "unreachable); exhausting the budget surfaces the ORIGINAL error"),
    ("serve_call_deadline_s", float, 0.0,
     "per-attempt deadline after which an unanswered replica call is "
     "treated as a dead replica and replayed elsewhere; 0 = disabled "
     "(rely on actor-death detection only)"),
    ("serve_health_check_period_s", float, 2.0,
     "controller-driven replica check_health probe cadence"),
    ("serve_health_check_timeout_s", float, 10.0,
     "an unanswered check_health probe older than this marks the "
     "replica wedged and restarts it"),
    ("serve_engine_stall_s", float, 10.0,
     "check_health fails when the engine has active slots but its step "
     "counter has not advanced for this long (hung jit step)"),
    ("serve_drain_grace_s", float, 10.0,
     "drain window granted to a replica's in-flight requests when its "
     "node is preempted without an explicit deadline"),
    # -- misc
    ("usage_stats_enabled", bool, True, "local usage tagging"),
    ("log_to_driver_batch_lines", int, 200,
     "worker-log lines per pubsub batch"),
]

_SYSTEM_CONFIG_ENV = "RAY_TPU_SYSTEM_CONFIG"


def _coerce(typ: type, raw: Any) -> Any:
    if typ is bool:
        if isinstance(raw, str):
            return raw.strip().lower() in ("1", "true", "yes", "on")
        return bool(raw)
    return typ(raw)


class Config:
    """Resolved flag values as attributes (see CONFIG_DEFS)."""

    def __init__(self, system_config: Optional[Dict[str, Any]] = None):
        sysconf = dict(system_config or {})
        raw_env = os.environ.get(_SYSTEM_CONFIG_ENV)
        if raw_env and not sysconf:
            try:
                sysconf = json.loads(raw_env)
            except ValueError:
                pass
        unknown = set(sysconf) - {n for n, *_ in CONFIG_DEFS}
        if unknown:
            raise ValueError(f"unknown _system_config keys: {sorted(unknown)}")
        self._explicit = set()
        for name, typ, default, _help in CONFIG_DEFS:
            env = os.environ.get(f"RAY_TPU_{name.upper()}")
            if env is not None:
                val = _coerce(typ, env)
                self._explicit.add(name)
            elif name in sysconf:
                val = _coerce(typ, sysconf[name])
                self._explicit.add(name)
            else:
                val = default
            setattr(self, name, val)

    def is_set(self, name: str) -> bool:
        """True when the flag was explicitly set (env or system config),
        as opposed to carrying its table default."""
        return name in self._explicit

    def to_dict(self) -> Dict[str, Any]:
        return {n: getattr(self, n) for n, *_ in CONFIG_DEFS}


_lock = threading.Lock()
_current: Optional[Config] = None


def cfg() -> Config:
    """The process-wide resolved config.

    Rebuilt from the environment on each call unless set_system_config
    pinned an explicit config — env flags stay live for processes (and
    tests) that set them after import; daemons resolve once at their
    read sites anyway."""
    with _lock:
        if _current is not None:
            return _current
        return Config()


def set_system_config(system_config: Optional[Dict[str, Any]]) -> None:
    """Install a system-config dict (driver side) and export it so
    spawned daemons inherit it (the reference propagates _system_config
    from `ray.init` through the raylet to every worker)."""
    global _current
    with _lock:
        _current = Config(system_config)
        if system_config:
            os.environ[_SYSTEM_CONFIG_ENV] = json.dumps(system_config)


def describe() -> str:
    """Human-readable flag table (`ray-tpu config`)."""
    c = cfg()
    lines = []
    for name, typ, default, help_ in CONFIG_DEFS:
        cur = getattr(c, name)
        mark = "" if cur == default else "  [overridden]"
        lines.append(f"{name:32s} {typ.__name__:5s} = {cur!r}{mark}\n"
                     f"{'':40s}{help_}")
    return "\n".join(lines)
