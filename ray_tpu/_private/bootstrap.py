"""Cluster bootstrap: start/stop control plane + raylet processes.

Analog of the reference's node bootstrap (reference:
python/ray/_private/node.py:1354 start_head_processes,
services.py:1442 start_gcs_server, :1507 start_raylet).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from . import common
from .protocol import Client, free_port

_SESSION_ROOT = "/dev/shm/ray_tpu"


def _wait_ping(addr: Tuple[str, int], timeout: float = 30.0, what: str = "daemon"):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            cli = Client(addr, connect_timeout=2.0)
            cli.call("ping", timeout=5.0)
            cli.close()
            return
        except Exception as e:
            last = e
            time.sleep(0.05)
    raise RuntimeError(f"{what} at {addr} did not come up: {last}")


class NodeHandle:
    def __init__(self, proc: subprocess.Popen, addr, node_id, session_dir):
        self.proc = proc
        self.addr = addr
        self.node_id = node_id
        self.session_dir = session_dir

    def kill(self):
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)

    def terminate(self):
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def _package_pythonpath() -> str:
    """PYTHONPATH entry that makes ray_tpu importable in child processes
    even when the driver found it via sys.path manipulation."""
    import ray_tpu

    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    existing = os.environ.get("PYTHONPATH", "")
    parts = [pkg_parent] + ([existing] if existing else [])
    return os.pathsep.join(parts)


def _spawn(cmd: List[str], log_path: str, env: Optional[Dict[str, str]] = None):
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    out = open(log_path, "ab")
    proc = subprocess.Popen(
        cmd, stdout=out, stderr=out,
        env={**os.environ, "PYTHONPATH": _package_pythonpath(), **(env or {})},
        start_new_session=True)
    out.close()
    return proc


class Cluster:
    """A local cluster: one control server + N raylets (each its own
    process).  The workhorse for tests, like the reference's
    ray.cluster_utils.Cluster (reference: python/ray/cluster_utils.py:135)."""

    def __init__(self, session_name: Optional[str] = None):
        self.session_name = session_name or f"session-{int(time.time()*1000)}-{os.getpid()}"
        self.session_dir = os.path.join(_SESSION_ROOT, self.session_name)
        os.makedirs(self.session_dir, exist_ok=True)
        self.log_dir = os.path.join(self.session_dir, "logs")
        self.control_proc: Optional[subprocess.Popen] = None
        self.standby_proc: Optional[subprocess.Popen] = None
        self.control_addr: Optional[Tuple[str, int]] = None
        self.nodes: List[NodeHandle] = []
        self._n = 0

    def start_control(self) -> Tuple[str, int]:
        port = free_port()
        self._spawn_control(port)
        self.control_addr = ("127.0.0.1", port)
        _wait_ping(self.control_addr, what="control plane")
        return self.control_addr

    @property
    def control_addr_file(self) -> str:
        return os.path.join(self.session_dir, "control_addr")

    def _spawn_control(self, port: int):
        cmd = [sys.executable, "-m", "ray_tpu._private.control",
               "--host", "127.0.0.1", "--port", str(port),
               "--addr-file", self.control_addr_file]
        # RAY_TPU_CONTROL_PERSIST also works via inherited env; the flag
        # keeps the subprocess's configuration visible in `ps`
        persist = os.environ.get("RAY_TPU_CONTROL_PERSIST")
        if persist:
            cmd += ["--persist", persist]
        self.control_proc = _spawn(
            cmd, os.path.join(self.log_dir, "control.log"))

    def start_standby(self) -> "subprocess.Popen":
        """Spawn a warm-standby controller: it watches the primary and,
        when the primary stops answering, loads the persisted state,
        starts serving on its own port, and rewrites the addr-file —
        raylets and drivers re-home to it on their next reconnect
        (reference analog: Redis-backed GCS fault tolerance, promoted
        to an active standby)."""
        assert self.control_addr is not None, "start_control() first"
        persist = os.environ.get("RAY_TPU_CONTROL_PERSIST")
        assert persist, "standby needs RAY_TPU_CONTROL_PERSIST"
        self.standby_port = free_port()
        cmd = [sys.executable, "-m", "ray_tpu._private.control",
               "--host", "127.0.0.1", "--port", str(self.standby_port),
               "--persist", persist,
               "--addr-file", self.control_addr_file,
               "--standby-of",
               f"{self.control_addr[0]}:{self.control_addr[1]}"]
        self.standby_proc = _spawn(
            cmd, os.path.join(self.log_dir, "control-standby.log"))
        return self.standby_proc

    def kill_control(self):
        """Hard-kill the control daemon (GCS failure injection)."""
        if self.control_proc is not None and self.control_proc.poll() is None:
            self.control_proc.kill()
            self.control_proc.wait(timeout=10)

    def restart_control(self):
        """Bring the control daemon back on the same address (reference:
        GCS restart under fault tolerance — ha_integration tests)."""
        assert self.control_addr is not None, "start_control() first"
        self.kill_control()
        self._spawn_control(self.control_addr[1])
        _wait_ping(self.control_addr, what="control plane")
        return self.control_addr

    def add_node(self, resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 wait: bool = True,
                 control_addr: Optional[Tuple[str, int]] = None,
                 use_addr_file: bool = True) -> NodeHandle:
        """control_addr/use_addr_file let partition tests route a raylet
        through a fault-injection proxy (test_utils.SocketProxy): the
        proxy address replaces the real control address, and the addr
        file is withheld so reconnects can't re-home around the fault."""
        assert self.control_addr is not None, "start_control() first"
        self._n += 1
        nid = common.node_id()
        port = free_port()
        node_session = os.path.join(self.session_dir, f"node-{self._n}")
        ctrl = tuple(control_addr) if control_addr else self.control_addr
        cmd = [sys.executable, "-m", "ray_tpu._private.node",
               "--control", f"{ctrl[0]}:{ctrl[1]}",
               "--host", "127.0.0.1", "--port", str(port),
               "--node-id", nid, "--session-dir", node_session]
        if use_addr_file:
            cmd += ["--addr-file", self.control_addr_file]
        if resources is not None:
            cmd += ["--resources", json.dumps(resources)]
        env = {}
        if labels:
            env["RAY_TPU_NODE_LABELS"] = json.dumps(labels)
        proc = _spawn(cmd, os.path.join(self.log_dir, f"raylet-{self._n}.log"), env)
        h = NodeHandle(proc, ("127.0.0.1", port), nid, node_session)
        self.nodes.append(h)
        if wait:
            _wait_ping(h.addr, what="raylet")
            # The raylet answers ping before its register_node round-trip
            # completes; callers doing get_nodes/report_draining right
            # after add_node raced that window.  Wait for the control
            # plane's view too (skipped for proxy-routed raylets, whose
            # registration may be deliberately severed mid-flight).
            if control_addr is None:
                self._wait_registered(nid)
        return h

    def _wait_registered(self, nid: str, timeout_s: float = 30.0):
        deadline = time.monotonic() + timeout_s
        last: object = None
        while time.monotonic() < deadline:
            try:
                cli = Client(self.control_addr, connect_timeout=2.0)
                nodes = cli.call("get_nodes", timeout=5.0)
                cli.close()
                if any(n.get("node_id") == nid for n in nodes):
                    return
            except Exception as e:
                last = e
            time.sleep(0.05)
        raise RuntimeError(
            f"raylet {nid} never appeared in control get_nodes: {last}")

    def remove_node(self, h: NodeHandle, graceful: bool = False):
        if graceful:
            h.terminate()
        else:
            h.kill()
        if h in self.nodes:
            self.nodes.remove(h)

    def shutdown(self):
        for h in list(self.nodes):
            h.terminate()
        self.nodes.clear()
        if self.standby_proc is not None and self.standby_proc.poll() is None:
            self.standby_proc.kill()
            try:
                self.standby_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        self.standby_proc = None
        if self.control_proc is not None and self.control_proc.poll() is None:
            self.control_proc.terminate()
            try:
                self.control_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.control_proc.kill()
        self.control_proc = None
        if os.environ.get("RAY_TPU_KEEP_SESSION"):
            return  # debugging: leave logs + store on disk
        import shutil

        shutil.rmtree(self.session_dir, ignore_errors=True)


# global session for ray_tpu.init()
_cluster: Optional[Cluster] = None


def start_local(num_cpus=None, num_tpus=None, resources=None) -> Tuple[Cluster, NodeHandle]:
    global _cluster
    c = Cluster()
    c.start_control()
    res = None
    if num_cpus is not None or num_tpus is not None or resources is not None:
        from . import accelerators

        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus if num_cpus is not None
                                    else (os.cpu_count() or 1)))
        tpus = num_tpus if num_tpus is not None else accelerators.num_tpu_chips()
        if tpus:
            res.setdefault("TPU", float(tpus))
    node = c.add_node(resources=res)
    _cluster = c
    return c, node
