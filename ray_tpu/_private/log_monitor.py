"""Per-node worker-log tailer -> control pubsub -> driver stderr.

The reference tails each worker's log files in a per-node log_monitor
process and publishes new lines to the driver through GCS pubsub
(reference: python/ray/_private/log_monitor.py) — that is how ``print``
inside a task reaches the driver console.  Here the tailer is a thread
inside the raylet: it follows ``logs/worker-*.log``, attributes lines to
jobs via inline job markers the workers emit (workers are shared across
jobs, unlike the reference's per-job workers), and publishes batches on
the ``worker_logs`` topic.  Driver cores subscribe and echo lines for
their job (``ray_tpu.init(log_to_driver=...)``).
"""

from __future__ import annotations

import glob
import logging
import os
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)

# Workers print this marker (alone on a line) when they start executing
# work for a different job; lines that follow belong to that job.
JOB_MARKER = "\x01RAYTPU-JOB "

from .config import cfg as _cfg

POLL_INTERVAL_S = 0.25
MAX_BATCH_LINES = _cfg().log_to_driver_batch_lines
MAX_LINE_LEN = 4000


class _FileState:
    __slots__ = ("offset", "job_id", "partial")

    def __init__(self):
        self.offset = 0
        self.job_id = ""      # last job marker seen in this file
        self.partial = b""    # trailing bytes with no newline yet


class LogMonitor:
    """Tails worker logs under `log_dir` and publishes new lines via
    `publish(payload)` (a callable hitting the control pubsub)."""

    def __init__(self, log_dir: str, node_id: str, publish):
        self.log_dir = log_dir
        self.node_id = node_id
        self.publish = publish
        self._files: Dict[str, _FileState] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        name="raylet-log-monitor",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(POLL_INTERVAL_S):
            try:
                self.poll_once()
            except Exception:
                logger.exception("log monitor poll failed")

    def poll_once(self):
        for path in glob.glob(os.path.join(self.log_dir, "worker-*.log")):
            st = self._files.get(path)
            if st is None:
                st = self._files[path] = _FileState()
            try:
                size = os.path.getsize(path)
                if size <= st.offset:
                    continue
                with open(path, "rb") as f:
                    f.seek(st.offset)
                    data = f.read(1 << 20)
                    st.offset = f.tell()
            except OSError:
                continue
            self._emit(path, st, st.partial + data)

    def _emit(self, path: str, st: _FileState, data: bytes):
        worker = os.path.basename(path)[len("worker-"):-len(".log")]
        lines = data.split(b"\n")
        st.partial = lines.pop()  # tail w/o newline waits for more bytes
        batch = []

        def flush():
            if batch:
                self.publish({"node_id": self.node_id, "worker_id": worker,
                              "job_id": st.job_id, "lines": list(batch)})
                batch.clear()

        for raw in lines:
            line = raw[:MAX_LINE_LEN].decode("utf-8", errors="replace")
            if line.startswith(JOB_MARKER):
                flush()  # lines before the marker belong to the old job
                st.job_id = line[len(JOB_MARKER):].strip()
                continue
            batch.append(line)
            if len(batch) >= MAX_BATCH_LINES:
                flush()
        flush()
