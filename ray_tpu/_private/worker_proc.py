"""Worker process main loop.

Analog of the reference worker: registers with its raylet using the startup
token (reference: worker_pool.h startup token protocol), then serves
push_task / actor_task RPCs (reference: CoreWorker::HandlePushTask
core_worker.cc:3489 -> scheduling queues -> ExecuteTask :2914).  Normal tasks
run sequentially on one executor thread; actor tasks run FIFO in arrival
order (TCP preserves per-caller order, giving the reference's per-caller
sequence semantics); max_concurrency>1 uses a thread pool like the
reference's concurrency groups.
"""

from __future__ import annotations

import argparse
import asyncio
import inspect
import logging
import os
import queue
import sys
import threading
import time
import traceback

import cloudpickle

from . import common, serialization
from .common import TaskError, TaskSpec
from .core import CoreWorker, ObjectRef
from .protocol import Deferred, ServerConn
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

_ASYNC_INFLIGHT = object()  # sentinel: reply will come from the aio loop


# ack coalescing knobs: while the worker's run queue is non-empty a
# completed reply may wait up to the linger for batchmates (and never
# longer than the hold cap in total) before its frame ships — the hold
# cap bounds how long a dependent task parked on ANOTHER worker can be
# stalled by ack framing.  An idle queue always flushes immediately, so
# sequential get() chains pay zero added latency.
ACK_LINGER_S = 0.002
ACK_HOLD_MAX_S = 0.005
ACK_BATCH_CAP = 64


class _ReplyBatcher:
    """Combining sender for coalesced task acks: completions are framed
    into `tasks_done` pushes on the owner connection (or, for
    mux-relayed tasks, one framed `mux_tasks_done` stream to the
    raylet).  With the worker's run queue idle the ack ships inline on
    the completing thread (the pre-linger latency path, bit-for-bit);
    under backlog a dedicated sender thread lingers briefly so
    back-to-back completions coalesce into one frame instead of one
    push per task."""

    def __init__(self, conn: ServerConn = None, send=None, backlog=None):
        # default transport: tasks_done pushes on the owner connection;
        # mux-relayed tasks instead ack through the raylet (one framed
        # mux_tasks_done stream per node, fanned back out to owners)
        self._conn = conn
        self._send = send if send is not None \
            else (lambda batch: conn.push("tasks_done", batch))
        # "more completions are imminent" probe (the worker's run-queue
        # emptiness); lingering is pointless — pure latency — without it
        self._backlog = backlog if backlog is not None else (lambda: False)
        self._cv = threading.Condition()
        self._pending: list = []        # guarded-by: _cv
        # (traceparent carrier, add-clock) per sampled ack awaiting its
        # frame — swapped out together with _pending so each ship pass
        # reports its own linger spans; wire batches stay 2-tuples
        self._tp_pending: list = []     # guarded-by: _cv
        self._thread = None             # guarded-by: _cv
        self._draining = False          # guarded-by: _cv

    def add(self, task_id: str, reply, tp=None):
        with self._cv:
            self._pending.append((task_id, reply))
            if tp is not None:
                self._tp_pending.append((tp, time.time_ns()))
            if self._draining:
                self._cv.notify()   # the active sender picks this up
                return
            if self._backlog():
                # more completions imminent: hand off to the linger
                # thread so this frame can fill up
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._run, name="ack-batcher", daemon=True)
                    self._thread.start()
                else:
                    self._cv.notify()
                return
            # idle queue: ship inline on the executor thread (it has
            # nothing else to do) — the exact pre-linger latency path
            self._draining = True
        self._drain()

    def _drain(self):
        """Send frames until _pending runs dry.  Caller owns _draining;
        acks landing while a frame is on the wire coalesce into the
        next one."""
        while True:
            with self._cv:
                batch, self._pending = self._pending, []
                traced, self._tp_pending = self._tp_pending, []
                if not batch:
                    self._draining = False
                    return
            if traced:
                self._emit_linger_spans(traced, len(batch))
            try:
                # push failure = owner gone; its on_disconnect resched-
                # ules.  Any other failure (one unserializable reply)
                # must not kill the sender for future acks.
                self._send(batch)
            except Exception:
                logger.exception("ack batch push failed")

    @staticmethod
    def _emit_linger_spans(traced, batch_n: int):
        """Retro worker.ack_linger spans: completion handed to the
        batcher -> its tasks_done frame actually shipping (the coalesce
        wait a sampled task's reply paid, with the frame it rode in)."""
        from ray_tpu.util import tracing

        now_ns = time.time_ns()
        for tp, add_ns in traced:
            tracing.record_span("worker.ack_linger", "INTERNAL", add_ns,
                                now_ns, tracing._extract(tp),
                                batch=batch_n)

    def _run(self):
        while True:
            with self._cv:
                while not self._pending or self._draining:
                    self._cv.wait(timeout=60.0)
                    if not self._pending and not self._draining \
                            and not getattr(self._conn, "alive", True):
                        # owner gone and nothing queued: let the thread
                        # die (a late add() starts a fresh one)
                        self._thread = None
                        return
                held0 = time.monotonic()
                while (len(self._pending) < ACK_BATCH_CAP
                       and self._backlog()
                       and time.monotonic() - held0 < ACK_HOLD_MAX_S):
                    n = len(self._pending)
                    self._cv.wait(timeout=ACK_LINGER_S)
                    if len(self._pending) == n:
                        break   # linger expired with no new completion
                self._draining = True
            self._drain()


class _BatchSlot:
    """Pseudo-Deferred for batch-pushed tasks: the execution pipeline
    resolves replies through the same interface either way, but here the
    reply routes into the per-connection ack batcher instead of a
    per-call reply frame."""

    __slots__ = ("_batcher", "_task_id", "_tp")

    def __init__(self, batcher: _ReplyBatcher, task_id: str, tp=None):
        self._batcher = batcher
        self._task_id = task_id
        self._tp = tp   # traceparent carrier when the task is sampled

    def resolve(self, reply):
        self._batcher.add(self._task_id, reply, tp=self._tp)

    def reject(self, exc):
        self._batcher.add(self._task_id, {
            "status": "error",
            "error": serialization.dumps_inline(exc)}, tp=self._tp)


class WorkerMain:
    def __init__(self, control_addr, raylet_addr):
        self.token = int(os.environ["RAY_TPU_STARTUP_TOKEN"])
        wid = os.environ.get("RAY_TPU_WORKER_ID")
        nid = os.environ.get("RAY_TPU_NODE_ID")
        session_dir = os.environ.get("RAY_TPU_SESSION_DIR")
        self.actor_id = os.environ.get("RAY_TPU_ACTOR_ID")
        self.incarnation = int(os.environ.get("RAY_TPU_ACTOR_INCARNATION", "0"))
        store_root = os.path.join(session_dir, "objects") if session_dir else None
        self.core = CoreWorker(control_addr, raylet_addr, mode="worker",
                               worker_id=wid, node_id=nid, store_root=store_root)
        self.core.server.handle("push_task", self.h_push_task, deferred=True)
        self.core.server.handle("push_tasks", self.h_push_tasks)
        self.core.server.handle("actor_task", self.h_actor_task, deferred=True)
        self.core.server.handle("exit", lambda c, p: self._exit_soon())
        self.core.server.handle("cancel_task", self.h_cancel_task)

        self.task_queue: "queue.Queue" = queue.Queue()
        # one reply batcher per owner connection (batched submissions)
        self._reply_batchers: dict = {}
        # lazily-built ack batcher for mux-relayed tasks (acks go to the
        # raylet, which fans them back out to the owning drivers)
        self._mux_batcher = None        # guarded-by: _batcher_lock
        self._batcher_lock = threading.Lock()
        # cancellation state (reference: core_worker HandleCancelTask):
        # queued task ids to drop + the id/thread of the running task
        self._cancelled: set = set()
        self._cancel_lock = threading.Lock()
        self._running_task: dict = {}  # thread ident -> task_id
        self._aio_tasks: dict = {}  # task_id -> asyncio.Task (async exec)
        self.actor_instance = None
        self.actor_concurrency = 1
        self._stop = threading.Event()
        # Async actors (reference: core_worker fiber.h / async actor event
        # loop): methods returning coroutines run on this loop; the exec
        # thread does NOT block on them — the Deferred resolves from the
        # loop when the coroutine finishes, so one actor can interleave
        # many in-flight async calls.
        self._aio_loop: asyncio.AbstractEventLoop = None
        self._aio_lock = threading.Lock()
        self._stream_executor = None  # created with the aio loop

        # raylet client push handling (shutdown) + death of raylet kills us
        self.core.raylet._on_push = self._on_raylet_push
        self.core.raylet._on_disconnect = self._exit_soon

        r = self.core.raylet.call("register_worker", {
            "token": self.token, "addr": self.core.addr,
        }, timeout=30.0)
        if not r.get("ok"):
            raise RuntimeError(f"worker registration rejected: {r}")

        # apply the driver-registered tracing startup hook, if any
        # (reference: tracing_helper.py hook runs in every worker)
        from ray_tpu.util import tracing

        tracing.apply_hook_from_kv(self.core.control)
        # the hook (or RAY_TPU_TRACE_SAMPLE) may have enabled tracing
        # after CoreWorker init skipped the collector — attach it now
        tracing.ensure_collector(
            self.core.control,
            proc=f"worker:{self.core.worker_id[:8]}",
            worker_id=self.core.worker_id,
            node_id=self.core.node_id or "", job_id=self.core.job_id)

        n_threads = 1
        self.exec_threads = [
            threading.Thread(target=self._exec_loop, name=f"exec-{i}", daemon=True)
            for i in range(n_threads)
        ]
        for t in self.exec_threads:
            t.start()

        if self.actor_id:
            threading.Thread(target=self._init_actor, daemon=True).start()

    # -- actor bootstrap ---------------------------------------------------

    def _init_actor(self):
        err = None
        try:
            # _control_call: a worker booting during a control-plane blip
            # reconnects and retries instead of failing actor creation
            blob = self.core._control_call("get_actor_spec",
                                           {"actor_id": self.actor_id},
                                           timeout=30.0)
            if blob is None:
                raise RuntimeError("actor spec missing in control plane")
            spec = cloudpickle.loads(blob)
            if spec.get("runtime_env"):
                from . import runtime_env as rtenv

                # env applies BEFORE deserializing the class/args (their
                # unpickling may import py_modules/working_dir code) and
                # lasts for the actor process lifetime
                rtenv.materialize(spec["runtime_env"],
                                  self.core.control).apply_permanent()
            cls = cloudpickle.loads(spec["class_blob"])
            args, kwargs = serialization.loads_inline(spec["args_blob"])
            args = [self.core.get(a) if isinstance(a, ObjectRef) else a
                    for a in args]
            kwargs = {k: self.core.get(v) if isinstance(v, ObjectRef) else v
                      for k, v in kwargs.items()}
            self.actor_instance = cls(*args, **kwargs)
            # async actors (any coroutine method) run ALL their methods on
            # the event-loop thread — the reference's async-actor model:
            # cooperative concurrency on one thread, sync methods block the
            # loop.  This keeps actor state single-threaded.
            self.actor_is_async = any(
                inspect.iscoroutinefunction(getattr(cls, m, None))
                for m in dir(cls) if not m.startswith("__"))
            self.actor_concurrency = spec.get("max_concurrency", 1) or 1
            if self.actor_concurrency > 1:
                for i in range(self.actor_concurrency - 1):
                    t = threading.Thread(target=self._exec_loop,
                                         name=f"exec-actor-{i}", daemon=True)
                    t.start()
                    self.exec_threads.append(t)
        except BaseException as e:
            err = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
            logger.error("actor creation failed: %s", err)
        try:
            self.core._control_call("actor_ready", {
                "actor_id": self.actor_id,
                "worker_addr": self.core.addr,
                "incarnation": self.incarnation,
                # lets the control plane adopt this placement even if its
                # start_actor_worker call failed mid-flight (reply lost)
                "node_id": os.environ.get("RAY_TPU_NODE_ID"),
                "error": err,
            }, timeout=30.0)
        except Exception:
            logger.exception("failed to report actor_ready")
        if err is not None:
            self._exit_soon()

    # -- rpc handlers ------------------------------------------------------

    @staticmethod
    def _trace_enqueue(spec) -> None:
        """Stamp the run-queue entry clock on sampled specs (local-only
        attr; feeds the retro worker.queue_wait span at dequeue)."""
        if tracing.is_enabled() and tracing.carrier_sampled(
                getattr(spec, "trace_ctx", None)):
            spec._enq_ns = time.time_ns()

    @staticmethod
    def _trace_tp(spec):
        """Traceparent carrier for sampled specs, else None (what the
        ack batcher needs to report linger spans).  Also stamps the
        run-queue entry clock — one sampling probe covers both, keeping
        the batched enqueue loops at a single call per spec."""
        if tracing.is_enabled() and tracing.carrier_sampled(
                getattr(spec, "trace_ctx", None)):
            spec._enq_ns = time.time_ns()
            return spec.trace_ctx
        return None

    def h_push_task(self, conn: ServerConn, spec: TaskSpec, d: Deferred):
        self._trace_enqueue(spec)
        self.task_queue.put(("normal", spec, d))

    def h_push_tasks(self, conn: ServerConn, specs):
        """Batched submission (one-way notify, no per-task reply slot):
        enqueue every framed spec FIFO; completions ack through the
        per-connection tasks_done batcher."""
        batcher = self._reply_batchers.get(conn)
        if batcher is None:
            with self._batcher_lock:
                batcher = self._reply_batchers.get(conn)
                if batcher is None:
                    # prune batchers of disconnected owners while here
                    for c in [c for c in self._reply_batchers
                              if not c.alive]:
                        del self._reply_batchers[c]
                    batcher = self._reply_batchers[conn] = \
                        _ReplyBatcher(conn, backlog=self._ack_backlog)
        for spec in specs:
            # actor calls ride the same framed envelopes since the owner
            # flusher batches them too — route by spec, not by handler
            kind = "actor" if spec.actor_id else "normal"
            self.task_queue.put(
                (kind, spec,
                 _BatchSlot(batcher, spec.task_id, self._trace_tp(spec))))

    def h_actor_task(self, conn: ServerConn, spec: TaskSpec, d: Deferred):
        self._trace_enqueue(spec)
        self.task_queue.put(("actor", spec, d))

    def h_cancel_task(self, conn: ServerConn, p):
        """Cancel a queued or running normal task (reference:
        CoreWorker::HandleCancelTask).  force kills the process; plain
        cancel injects TaskCancelledError into the executing thread."""
        tid = p.get("task_id")
        force = p.get("force", False)
        recursive = p.get("recursive", False)
        with self._cancel_lock:
            # async task/actor-method first: looked up under _cancel_lock,
            # the same lock _register_aio claims under — a cancel either
            # finds the registered asyncio.Task or parks in _cancelled
            # for _register_aio to observe before running the coroutine
            entry = self._aio_tasks.get(tid)
            if entry is not None:
                aio_task, aio_kind = entry
                if force and aio_kind == "normal":
                    # force semantics are unchanged for normal tasks:
                    # kill the process (a stuck/shielded coroutine never
                    # observes a soft cancel)
                    os._exit(1)
                loop = self._aio_loop
                if loop is not None:
                    loop.call_soon_threadsafe(aio_task.cancel)
            else:
                running_thread = next(
                    (th for th, t in self._running_task.items()
                     if t == tid), None)
                if running_thread is None:
                    self._cancelled.add(tid)
                elif force:
                    os._exit(1)
                else:
                    import ctypes

                    from .common import TaskCancelledError

                    # inject while still holding the lock: the exec loop
                    # clears _running_task under this same lock, so the
                    # exception can only be scheduled while the task is
                    # genuinely the current one (a late landing between
                    # tasks is absorbed by _exec_loop)
                    ctypes.pythonapi.PyThreadState_SetAsyncExc(
                        ctypes.c_ulong(running_thread),
                        ctypes.py_object(TaskCancelledError))
        if recursive:
            # children submitted BY the cancelled task are owned by this
            # process — cancel them off the server thread (they may need
            # RPCs of their own)
            self.core.pool_executor.submit(
                self.core.cancel_children, tid, force)
        return True

    def _mux_batcher_get(self) -> _ReplyBatcher:
        with self._batcher_lock:
            if self._mux_batcher is None:
                raylet = self.core.raylet
                self._mux_batcher = _ReplyBatcher(
                    send=lambda batch: raylet.notify(
                        "mux_tasks_done", batch),
                    backlog=self._ack_backlog)
            return self._mux_batcher

    def _ack_backlog(self) -> bool:
        """More completions imminent? drives ack-frame lingering."""
        return not self.task_queue.empty()

    def _on_raylet_push(self, topic, payload):
        if topic == "shutdown":
            self._exit_soon()
        elif topic == "mux_push_tasks":
            # relay-routed batch from the raylet: same execution pipeline
            # as h_push_tasks, but acks flow back through the raylet
            batcher = self._mux_batcher_get()
            for spec in payload:
                kind = "actor" if spec.actor_id else "normal"
                self.task_queue.put(
                    (kind, spec,
                     _BatchSlot(batcher, spec.task_id, self._trace_tp(spec))))
        elif topic == "mux_cancel":
            self.h_cancel_task(None, payload)
        elif topic == "assign_actor":
            # prestarted-worker reuse (reference: worker_pool.h PopWorker):
            # a warm idle worker becomes this actor's dedicated process,
            # skipping the interpreter + jax import cost of a fresh spawn
            self.actor_id = payload["actor_id"]
            self.incarnation = payload.get("incarnation", 0)
            threading.Thread(target=self._init_actor, daemon=True).start()
        else:
            # core-level pushes (reclaim_idle_leases etc.)
            self.core._on_raylet_push(topic, payload)

    def _exit_soon(self):
        self._stop.set()
        threading.Thread(target=self._do_exit, daemon=True).start()
        return True

    def _do_exit(self):
        time.sleep(0.05)
        os._exit(0)

    # -- execution ---------------------------------------------------------

    def _exec_loop(self):
        from .common import TaskCancelledError

        while not self._stop.is_set():
            try:
                self._exec_one()
            except TaskCancelledError:
                # a cancel injection that landed after its task already
                # finished (between tasks); the cancel is void — survive
                continue
            except Exception:
                logger.exception("exec loop error")

    def _exec_one(self):
        from .common import TaskCancelledError

        try:
            kind, spec, d = self.task_queue.get(timeout=0.2)
        except queue.Empty:
            return
        enq_ns = getattr(spec, "_enq_ns", None)
        if enq_ns is not None:
            spec._enq_ns = None
            from ray_tpu.util import tracing

            tracing.record_span(
                "worker.queue_wait", "INTERNAL", enq_ns, time.time_ns(),
                tracing._extract(spec.trace_ctx),
                queue_depth=self.task_queue.qsize())
        with self._cancel_lock:
            if spec.task_id in self._cancelled:
                self._cancelled.discard(spec.task_id)
                cancelled = True
            else:
                cancelled = False
                self._running_task[threading.get_ident()] = spec.task_id
        if cancelled:
            d.resolve(self._error_reply(
                TaskCancelledError("cancelled before start"), spec))
            return
        reply = None
        try:
            try:
                from ray_tpu.util import tracing

                with tracing.execute_span(
                        "task" if kind == "normal" else "actor",
                        spec.function_name,
                        getattr(spec, "trace_ctx", None),
                        task_id=spec.task_id, actor_id=spec.actor_id):
                    reply = self._execute(kind, spec, d)
            except TaskCancelledError as e:
                # injection landed inside _execute's own error handling;
                # still owe the owner a reply
                reply = self._error_reply(e, spec)
        finally:
            # a cancel injected while _execute was unwinding may land at
            # any bytecode below; keep clearing + resolving until it's
            # done (at most one async exc can be pending)
            for _attempt in range(3):
                try:
                    with self._cancel_lock:
                        self._running_task.pop(threading.get_ident(), None)
                    if reply is not None and reply is not _ASYNC_INFLIGHT:
                        d.resolve(reply)
                        reply = None
                    break
                except TaskCancelledError:
                    continue

    def _register_aio(self, spec: TaskSpec, kind: str = "normal") -> bool:
        """First statement of every async execution coroutine: atomically
        either claim the task (register its asyncio.Task for
        cancellation) or observe a cancel that arrived before the loop
        ran us.  Returns False when already cancelled.  Also stamps the
        execution contextvars — each asyncio Task has its own context,
        so interleaved async methods attribute children correctly."""
        from .core import EXECUTING_JOB_ID, EXECUTING_TASK_ID

        with self._cancel_lock:
            if spec.task_id in self._cancelled:
                self._cancelled.discard(spec.task_id)
                return False
            self._aio_tasks[spec.task_id] = (asyncio.current_task(), kind)
        EXECUTING_TASK_ID.set(spec.task_id)
        EXECUTING_JOB_ID.set(getattr(spec, "job_id", "") or None)
        return True

    def _get_aio_loop(self) -> asyncio.AbstractEventLoop:
        with self._aio_lock:
            if self._aio_loop is None:
                from concurrent.futures import ThreadPoolExecutor

                loop = asyncio.new_event_loop()

                def _mark_executing():
                    # blocking get() from the loop thread (or from
                    # run_in_executor workers) must still notify the raylet
                    # it is blocked, else CPU slots are never lent back
                    self.core._executing.active = True

                loop.set_default_executor(ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix="actor-aio-exec",
                    initializer=_mark_executing))
                # streaming generators get their OWN pool: each in-flight
                # stream pins a thread for its whole duration, and 8
                # long-lived streams (SSE clients) would otherwise starve
                # every other blocking hop on the default executor
                self._stream_executor = ThreadPoolExecutor(
                    max_workers=64, thread_name_prefix="actor-stream",
                    initializer=_mark_executing)

                def _loop_main():
                    _mark_executing()
                    asyncio.set_event_loop(loop)
                    loop.run_forever()

                t = threading.Thread(target=_loop_main, name="actor-aio",
                                     daemon=True)
                t.start()
                self._aio_loop = loop
            return self._aio_loop

    WINDOW = 8  # in-flight unacked item reports per generator

    def _run_generator(self, spec: TaskSpec, out, t0: float):
        """Execute a streaming task: push each yielded item to the owner
        as it is produced (reference: HandleReportGeneratorItemReturns,
        task_manager.h:355).  The per-item acks double as backpressure —
        the owner defers them while its unconsumed buffer is full."""
        if not hasattr(out, "__iter__"):
            raise TypeError(
                f"task {spec.function_name} declared "
                f'num_returns="streaming" but returned non-iterable '
                f"{type(out).__name__}")
        from collections import deque

        owner = self.core._owner_client(tuple(spec.owner_addr))
        outstanding = deque()
        count = 0
        stopped = False

        def drain(limit: int):
            nonlocal stopped
            while len(outstanding) > limit:
                ack = outstanding.popleft().result(timeout=600.0)
                if ack and ack.get("stop"):
                    stopped = True
                    return

        try:
            for item in out:
                result = self.core.store_stream_item(spec, count, item)
                outstanding.append(owner.call_async(
                    "generator_item",
                    {"task_id": spec.task_id, "index": count,
                     "result": result}))
                count += 1
                drain(self.WINDOW - 1)
                if stopped:
                    break
        except BaseException:
            # make sure every already-yielded item is acked by the owner
            # BEFORE the error reply: the reply rides a different
            # connection and must not overtake the items
            try:
                drain(0)
            except Exception:
                pass
            raise
        finally:
            close = getattr(out, "close", None)
            if stopped and close is not None:
                close()
        drain(0)
        self.core.task_events.record_status(
            spec.task_id, "FINISHED", name=spec.function_name)
        return {"status": "ok", "streaming_done": count,
                "exec_ms": (time.monotonic() - t0) * 1000.0}

    def _store_reply(self, spec: TaskSpec, out, t0: float):
        if spec.num_returns > 1:
            values = list(out)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"task {spec.function_name} declared num_returns="
                    f"{spec.num_returns} but returned {len(values)} values")
        else:
            values = [out]
        reply = self.core.store_task_results(spec, values)
        reply["exec_ms"] = (time.monotonic() - t0) * 1000.0
        self.core.task_events.record_status(
            spec.task_id, "FINISHED", name=spec.function_name,
            actor_id=spec.actor_id)
        return reply

    def _error_reply(self, e: BaseException, spec: TaskSpec):
        tb = traceback.format_exc()
        self.core.task_events.record_status(
            spec.task_id, "FAILED", name=spec.function_name,
            actor_id=spec.actor_id, error=f"{type(e).__name__}: {e}")
        try:
            err_blob = serialization.dumps_inline(
                TaskError(e, tb, spec.function_name))
        except BaseException:
            err_blob = serialization.dumps_inline(
                TaskError(RuntimeError(f"{type(e).__name__}: {e}"), tb,
                          spec.function_name))
        return {"status": "error", "error": err_blob}

    _last_job_marker: str = None

    def _execute(self, kind: str, spec: TaskSpec, d: Deferred = None):
        from .core import EXECUTING_JOB_ID, EXECUTING_TASK_ID

        self.core._executing.active = True
        # children submitted while this task runs carry it as parent
        # (ray.cancel(recursive=True)) and keep the root driver's job
        # (log routing); contextvars so async tasks attribute per-Task
        EXECUTING_TASK_ID.set(spec.task_id)
        EXECUTING_JOB_ID.set(getattr(spec, "job_id", "") or None)
        # job marker: the raylet's log tailer attributes the stdout that
        # follows to this job (workers are shared across jobs here,
        # unlike the reference's per-job workers — log_monitor.py)
        job = getattr(spec, "job_id", "") or ""
        if job != self._last_job_marker:
            self._last_job_marker = job
            print(f"\x01RAYTPU-JOB {job}", flush=True)
        t0 = time.monotonic()
        self.core.task_events.record_status(
            spec.task_id, "RUNNING", name=spec.function_name,
            actor_id=spec.actor_id)
        try:
            if kind == "actor":
                if spec.function_name == "__ray_terminate__":
                    # graceful release (reference: the owner handle going
                    # out of scope queues __ray_terminate__ BEHIND pending
                    # calls; the actor drains, then exits).  Reply first,
                    # then mark DEAD at the control (so the exit isn't
                    # "restarted"), then exit.
                    d.resolve(self._store_reply(spec, None, t0))
                    try:
                        self.core._control_call(
                            "kill_actor",
                            {"actor_id": spec.actor_id,
                             "no_restart": True}, timeout=10.0)
                    except Exception:
                        pass
                    self._exit_soon()
                    return _ASYNC_INFLIGHT
                # wait for actor init to finish (creation runs async)
                deadline = time.monotonic() + 120.0
                while self.actor_instance is None and time.monotonic() < deadline \
                        and not self._stop.is_set():
                    time.sleep(0.005)
                if self.actor_instance is None:
                    raise common.ActorDiedError("actor instance not initialized")
                if spec.function_name == "__apply__":
                    # free function applied to the actor instance
                    # (reference: ActorHandle.__ray_call__) — powers
                    # compiled-graph exec loops without user-class changes
                    inst = self.actor_instance

                    def fn(_f, *a, **k):
                        return _f(inst, *a, **k)
                else:
                    fn = getattr(self.actor_instance, spec.function_name)
                if getattr(self, "actor_is_async", False):
                    # async actor: invoke on the event loop (even sync
                    # methods — they block the loop, the reference's
                    # semantics) so actor state stays single-threaded; the
                    # Deferred resolves from the loop and this exec thread
                    # moves on to the next queued task.
                    args, kwargs = self.core.resolve_args(spec)

                    async def _finish(spec=spec, t0=t0, d=d):
                        if not self._register_aio(spec, kind="actor"):
                            d.resolve(self._error_reply(
                                common.TaskCancelledError(
                                    "cancelled before start"), spec))
                            return
                        from ray_tpu.util import tracing

                        try:
                            with tracing.execute_span(
                                    "actor", spec.function_name,
                                    getattr(spec, "trace_ctx", None),
                                    task_id=spec.task_id,
                                    actor_id=spec.actor_id):
                                out = fn(*args, **kwargs)
                                if inspect.iscoroutine(out):
                                    out = await out
                                if spec.num_returns == \
                                        common.STREAMING_RETURNS:
                                    # sync generator method on an async
                                    # actor: stream from the dedicated
                                    # stream pool, not the loop (acks
                                    # block) nor the 8-thread default
                                    # executor (streams are long-lived)
                                    loop = asyncio.get_running_loop()
                                    reply = await loop.run_in_executor(
                                        self._stream_executor,
                                        self._run_generator,
                                        spec, out, t0)
                                else:
                                    reply = self._store_reply(spec, out,
                                                              t0)
                        except asyncio.CancelledError:
                            reply = self._error_reply(
                                common.TaskCancelledError(
                                    f"actor task {spec.function_name} "
                                    f"was cancelled"), spec)
                        except BaseException as e:
                            reply = self._error_reply(e, spec)
                        finally:
                            self._aio_tasks.pop(spec.task_id, None)
                        d.resolve(reply)

                    asyncio.run_coroutine_threadsafe(_finish(),
                                                     self._get_aio_loop())
                    return _ASYNC_INFLIGHT
            else:
                fn = self.core.get_function(spec.function_id)
            ctx = None
            if kind != "actor" and spec.runtime_env:
                from . import runtime_env as rtenv

                # enter the env BEFORE deserializing args: py_modules /
                # working_dir code may be needed at unpickle time
                ctx = rtenv.materialize(spec.runtime_env, self.core.control)
                ctx.__enter__()
            try:
                args, kwargs = self.core.resolve_args(spec)
                out = fn(*args, **kwargs)
            except BaseException:
                if ctx is not None:
                    ctx.__exit__(None, None, None)
                    ctx = None
                raise
            if spec.num_returns == common.STREAMING_RETURNS:
                try:
                    return self._run_generator(spec, out, t0)
                finally:
                    if ctx is not None:
                        ctx.__exit__(None, None, None)
            if inspect.iscoroutine(out):
                # async function task: run to completion on the loop; the
                # env context stays open until the coroutine finishes
                async def _finish(coro=out, spec=spec, t0=t0, d=d, ctx=ctx):
                    if not self._register_aio(spec):
                        coro.close()
                        if ctx is not None:
                            ctx.__exit__(None, None, None)
                        d.resolve(self._error_reply(
                            common.TaskCancelledError(
                                "cancelled before start"), spec))
                        return
                    from ray_tpu.util import tracing

                    try:
                        with tracing.execute_span(
                                "task", spec.function_name,
                                getattr(spec, "trace_ctx", None),
                                task_id=spec.task_id):
                            value = await coro
                        reply = self._store_reply(spec, value, t0)
                    except asyncio.CancelledError:
                        reply = self._error_reply(
                            common.TaskCancelledError(
                                f"task {spec.function_name} was "
                                f"cancelled"), spec)
                    except BaseException as e:
                        reply = self._error_reply(e, spec)
                    finally:
                        self._aio_tasks.pop(spec.task_id, None)
                        if ctx is not None:
                            ctx.__exit__(None, None, None)
                    d.resolve(reply)

                asyncio.run_coroutine_threadsafe(_finish(),
                                                 self._get_aio_loop())
                return _ASYNC_INFLIGHT
            if ctx is not None:
                ctx.__exit__(None, None, None)
            return self._store_reply(spec, out, t0)
        except BaseException as e:
            return self._error_reply(e, spec)
        finally:
            self.core._executing.active = False
            EXECUTING_TASK_ID.set(None)
            EXECUTING_JOB_ID.set(None)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--raylet", required=True)
    ap.add_argument("--control", required=True)
    args = ap.parse_args()
    # `kill -USR1 <worker pid>` dumps all thread stacks to a per-pid file
    # — the py-spy-dump analog for diagnosing wedged workers (reference:
    # dashboard ReporterAgent stack dumps).  The file is created lazily
    # on the first signal so worker churn doesn't litter /tmp.
    try:
        import faulthandler
        import signal

        def _dump_stacks(signum, frame):
            with open(f"/tmp/ray_tpu_worker_stacks_{os.getpid()}.txt",
                      "w") as f:
                faulthandler.dump_traceback(file=f)

        signal.signal(signal.SIGUSR1, _dump_stacks)
    except (AttributeError, OSError, ValueError):
        pass
    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s worker[{os.getpid()}] %(levelname)s %(message)s")
    rh, rp = args.raylet.rsplit(":", 1)
    ch, cp = args.control.rsplit(":", 1)
    w = WorkerMain((ch, int(cp)), (rh, int(rp)))
    try:
        while not w._stop.is_set():
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
