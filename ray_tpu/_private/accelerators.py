"""TPU accelerator detection & topology labels.

Mirror of the reference's accelerator-manager layer
(reference: python/ray/_private/accelerators/tpu.py:71 TPUAcceleratorManager
— chip detection via /dev/accel*, GCE metadata probing :48
_get_tpu_metadata, TPU_VISIBLE_CHIPS env :155-195).

Detection precedence per field: GKE env vars (TPU_NAME /
TPU_WORKER_ID / TPU_ACCELERATOR_TYPE, preset by the webhook) first,
then the GCE instance-metadata server (gcloud-provisioned TPU VMs carry
no env but always have metadata).  Worker 0 of a pod additionally
exposes the `TPU-<pod_type>-head` resource (reference: tpu.py:381) —
the handle gang schedulers target to run exactly one coordinator per
pod slice.
"""

from __future__ import annotations

import glob
import os
import re
import threading
from typing import Dict, Optional, Tuple

# GCE VM instance metadata (reference: tpu.py:23-29; endpoint
# overridable so tests point it at a fake metadata server)
_DEFAULT_METADATA_ENDPOINT = (
    "http://metadata.google.internal/computeMetadata/v1/instance/attributes")
_METADATA_KEYS = {"accelerator_type": "accelerator-type",
                  "tpu_name": "instance-id",
                  "worker_id": "agent-worker-number"}
_ACCEL_TYPE_RE = re.compile(r"^v\d+[a-zA-Z]*-\d+$")

_meta_lock = threading.Lock()
_meta_cache: Dict[str, Optional[str]] = {}
_meta_dead = False  # no metadata server here; stop re-probing


def _metadata_endpoint() -> str:
    return os.environ.get("RAY_TPU_GCE_METADATA_ENDPOINT",
                          _DEFAULT_METADATA_ENDPOINT)


def _get_tpu_metadata(key: str) -> Optional[str]:
    """One metadata attribute, or None (reference: tpu.py:48).  A failed
    connect marks the server dead for the process — laptops and non-GCE
    clusters pay the probe timeout once, not per call."""
    global _meta_dead
    with _meta_lock:
        if key in _meta_cache:
            return _meta_cache[key]
        if _meta_dead:
            return None
    import urllib.error
    import urllib.request

    val: Optional[str] = None
    try:
        req = urllib.request.Request(
            f"{_metadata_endpoint()}/{key}",
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=1.0) as r:
            if r.status == 200:
                val = r.read().decode().strip() or None
    except urllib.error.HTTPError:
        # 404/5xx: the server is ALIVE (an absent attribute is normal on
        # some shapes) — cache the miss for this key only
        val = None
    except OSError:
        # connection-level failure: no metadata server here
        with _meta_lock:
            _meta_dead = True
        return None
    except Exception:
        val = None
    with _meta_lock:
        _meta_cache[key] = val
    return val


def _reset_metadata_cache() -> None:
    """Test hook: forget probe results (endpoint changed)."""
    global _meta_dead
    with _meta_lock:
        _meta_cache.clear()
        _meta_dead = False


def num_tpu_chips() -> int:
    env = os.environ.get("RAY_TPU_NUM_CHIPS")
    if env:
        return int(env)
    chips = glob.glob("/dev/accel*")
    if chips:
        return len(chips)
    # axon remote-TPU tunnel (dev boxes): one chip endpoint per pool IP.
    # Without this the tunnel chip is invisible to the scheduler, so no
    # actor can ever be granted the TPU resource that node.py uses to
    # gate device access.
    pool = os.environ.get("PALLAS_AXON_POOL_IPS")
    if pool:
        return len([ip for ip in pool.split(",") if ip.strip()])
    # vfio-bound chips (reference: tpu.py get_current_node_num_accelerators)
    try:
        vfio = [e for e in os.listdir("/dev/vfio") if e.isdigit()]
        if vfio:
            return len(vfio)
    except FileNotFoundError:
        pass
    bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")  # e.g. "2,2,1"
    if bounds:
        n = 1
        for p in bounds.split(","):
            n *= int(p)
        return n
    return 0


def current_pod_type() -> Optional[str]:
    """Validated pod type, e.g. "v4-16" (reference: tpu.py
    _get_current_node_tpu_pod_type — GKE env, then GCE metadata)."""
    acc = os.environ.get("TPU_ACCELERATOR_TYPE")
    if not acc and num_tpu_chips():
        acc = _get_tpu_metadata(_METADATA_KEYS["accelerator_type"])
    if acc and _ACCEL_TYPE_RE.match(acc):
        return acc
    return None


def current_tpu_name() -> Optional[str]:
    """Pod/slice name (reference: tpu.py get_current_node_tpu_name)."""
    name = os.environ.get("TPU_NAME")
    if name:
        return name.split(",")[0]
    if num_tpu_chips():
        return _get_tpu_metadata(_METADATA_KEYS["tpu_name"])
    return None


def current_worker_id() -> Optional[int]:
    """This host's index within the pod (reference: tpu.py
    _get_current_node_tpu_worker_id)."""
    wid = os.environ.get("TPU_WORKER_ID")
    if not wid and num_tpu_chips():
        wid = _get_tpu_metadata(_METADATA_KEYS["worker_id"])
    try:
        return int(wid) if wid is not None and wid != "" else None
    except ValueError:
        return None


def tpu_labels() -> Dict[str, str]:
    labels = {}
    name = current_tpu_name()
    if not name:
        hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        name = hosts.split(",")[0] if hosts else None
    if name:
        labels["tpu_slice"] = name
    wid = current_worker_id()
    if wid is not None:
        labels["tpu_worker_id"] = str(wid)
    acc = current_pod_type()
    if acc:
        labels["tpu_accelerator_type"] = acc
    return labels


def pod_resources() -> Dict[str, float]:
    """Per-pod custom resources (reference: tpu.py:381
    get_additional_resources): every pod host exposes {<tpu_name>: 1};
    worker 0 additionally exposes {TPU-<pod_type>-head: 1} — request it
    to land exactly one coordinating task per pod slice."""
    out: Dict[str, float] = {}
    name = current_tpu_name()
    wid = current_worker_id()
    pod_type = current_pod_type()
    if name and wid is not None and pod_type:
        out[name] = 1.0
        if wid == 0:
            out[f"TPU-{pod_type}-head"] = 1.0
    return out


def default_resources() -> Dict[str, float]:
    res: Dict[str, float] = {"CPU": float(os.cpu_count() or 1)}
    chips = num_tpu_chips()
    if chips:
        res["TPU"] = float(chips)
        res.update(pod_resources())
    return res


def visible_chip_env(assigned: Tuple[int, ...]) -> Dict[str, str]:
    """Env vars confining a worker to its assigned chips
    (reference: tpu.py:155-195 set_current_process_visible_accelerator_ids)."""
    return {"TPU_VISIBLE_CHIPS": ",".join(str(c) for c in assigned)}


def tpu_device_paths() -> list:
    """Host device nodes a TPU container must be granted
    (reference: image_uri.py device propagation): /dev/accel* for
    direct-attached chips, the vfio group nodes + /dev/vfio/vfio
    control node for vfio-bound ones.  RAY_TPU_TPU_DEVICES overrides
    (exotic device layouts, tests)."""
    env = os.environ.get("RAY_TPU_TPU_DEVICES")
    if env is not None:
        return [p for p in env.split(",") if p]
    devs = sorted(glob.glob("/dev/accel*"))
    try:
        vfio = [f"/dev/vfio/{e}" for e in os.listdir("/dev/vfio")
                if e.isdigit()]
        if vfio:
            devs += ["/dev/vfio/vfio", *sorted(vfio)]
    except FileNotFoundError:
        pass
    return devs


#: host env a TPU container needs forwarded (the runtime does not
#: inherit its client's environment): chip visibility + topology
#: bounds + the axon-tunnel endpoint on tunnel dev boxes
_TPU_FORWARD_ENV = ("TPU_VISIBLE_CHIPS", "TPU_CHIPS_PER_HOST_BOUNDS",
                    "TPU_HOST_BOUNDS", "TPU_WORKER_ID",
                    "TPU_WORKER_HOSTNAMES", "TPU_NAME",
                    "PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")


def tpu_container_env() -> Dict[str, str]:
    """Env to forward into a TPU actor's container.  TPU_VISIBLE_CHIPS
    defaults to every host chip when unset (one TPU worker per host
    owns the slice's local chips, like the reference's whole-host TPU
    scheduling)."""
    out = {k: os.environ[k] for k in _TPU_FORWARD_ENV if k in os.environ}
    if out.get("JAX_PLATFORMS", "").lower() == "cpu":
        # a host pinned to CPU (dev boxes keep host processes off the
        # chip) must NOT pin the TPU actor's container to CPU — that is
        # the silent-fallback-while-holding-the-lease failure mode
        del out["JAX_PLATFORMS"]
    if "TPU_VISIBLE_CHIPS" not in out:
        chips = num_tpu_chips()
        if chips:
            out.update(visible_chip_env(tuple(range(chips))))
    return out
