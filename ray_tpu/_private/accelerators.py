"""TPU accelerator detection & topology labels.

Mirror of the reference's accelerator-manager layer
(reference: python/ray/_private/accelerators/tpu.py:71 TPUAcceleratorManager
— chip detection via GCE metadata :48, TPU_VISIBLE_CHIPS env :155-195).
We detect chips from /dev/accel* (TPU VMs expose one per chip), or the
GCE metadata env mirrors, or RAY_TPU_NUM_CHIPS; topology labels
(slice name, worker id, accelerator type) come from the standard TPU env
vars so gang placement can keep bundles on one ICI-connected slice.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Optional, Tuple


def num_tpu_chips() -> int:
    env = os.environ.get("RAY_TPU_NUM_CHIPS")
    if env:
        return int(env)
    chips = glob.glob("/dev/accel*")
    if chips:
        return len(chips)
    bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")  # e.g. "2,2,1"
    if bounds:
        n = 1
        for p in bounds.split(","):
            n *= int(p)
        return n
    return 0


def tpu_labels() -> Dict[str, str]:
    labels = {}
    slice_name = os.environ.get("TPU_NAME") or os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if slice_name:
        labels["tpu_slice"] = slice_name.split(",")[0]
    wid = os.environ.get("TPU_WORKER_ID")
    if wid is not None:
        labels["tpu_worker_id"] = wid
    acc = os.environ.get("TPU_ACCELERATOR_TYPE")
    if acc:
        labels["tpu_accelerator_type"] = acc
    return labels


def default_resources() -> Dict[str, float]:
    res: Dict[str, float] = {"CPU": float(os.cpu_count() or 1)}
    chips = num_tpu_chips()
    if chips:
        res["TPU"] = float(chips)
    return res


def visible_chip_env(assigned: Tuple[int, ...]) -> Dict[str, str]:
    """Env vars confining a worker to its assigned chips
    (reference: tpu.py:155-195 set_current_process_visible_accelerator_ids)."""
    return {"TPU_VISIBLE_CHIPS": ",".join(str(c) for c in assigned)}
