"""Control-plane state persistence — GCS fault tolerance equivalent.

Reference parity: GCS metadata storage is pluggable
(src/ray/gcs/store_client/in_memory_store_client.h vs
redis_store_client.h); with Redis configured, a restarted GCS reloads
``GcsInitData`` and raylets re-sync against it (the ``ha_integration``
test path, gcs_init_data.h).  Here the durable backend is sqlite on
local/shared disk: the control daemon writes through every metadata
mutation (KV, functions, jobs, actors, placement groups) and reloads the
tables on boot; raylets reconnect-and-reregister instead of exiting when
the control connection drops.

sqlite is the right shape for this role on a single control host: one
file, transactional, crash-safe (WAL), zero extra processes — the
"Redis" of the deployment without a second daemon to supervise.
"""

from __future__ import annotations

import pickle
import sqlite3
import threading
from typing import Any, Dict, Optional


class ControlStateStore:
    """Write-through durable store for control-plane tables.

    Two tables:
      kv(ns, k, v)        — the user/internal KV store, values as blobs
      records(tbl, key, data) — pickled metadata records per subsystem
                                (``actor``, ``pg``, ``job``, ``function``)
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            "ns TEXT, k TEXT, v BLOB, PRIMARY KEY (ns, k))")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS records ("
            "tbl TEXT, key TEXT, data BLOB, PRIMARY KEY (tbl, key))")
        self._db.commit()

    # -- kv ----------------------------------------------------------------

    def kv_put(self, ns: str, key: str, val: bytes) -> None:
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO kv (ns, k, v) VALUES (?, ?, ?)",
                (ns, key, sqlite3.Binary(val)))
            self._db.commit()

    def kv_del(self, ns: str, key: str) -> None:
        with self._lock:
            self._db.execute("DELETE FROM kv WHERE ns = ? AND k = ?",
                             (ns, key))
            self._db.commit()

    def load_kv(self) -> Dict[str, Dict[str, bytes]]:
        out: Dict[str, Dict[str, bytes]] = {}
        with self._lock:
            for ns, k, v in self._db.execute("SELECT ns, k, v FROM kv"):
                out.setdefault(ns, {})[k] = bytes(v)
        return out

    # -- records -----------------------------------------------------------

    def rec_put(self, tbl: str, key: str, obj: Any) -> None:
        blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO records (tbl, key, data) "
                "VALUES (?, ?, ?)", (tbl, key, sqlite3.Binary(blob)))
            self._db.commit()

    def rec_del(self, tbl: str, key: str) -> None:
        with self._lock:
            self._db.execute(
                "DELETE FROM records WHERE tbl = ? AND key = ?", (tbl, key))
            self._db.commit()

    def load_table(self, tbl: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        with self._lock:
            rows = self._db.execute(
                "SELECT key, data FROM records WHERE tbl = ?", (tbl,))
            for key, data in rows:
                out[key] = pickle.loads(bytes(data))
        return out

    def close(self) -> None:
        with self._lock:
            try:
                self._db.commit()
                self._db.close()
            except sqlite3.Error:
                pass


def open_store(path: Optional[str]) -> Optional[ControlStateStore]:
    if not path:
        return None
    import os

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    return ControlStateStore(path)
