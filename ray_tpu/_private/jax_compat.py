"""Version portability for jax APIs that moved between releases.

The kernels and parallelism modules target the modern surface —
``jax.shard_map`` (promoted to top-level in jax 0.6) with ``check_vma=``
for the varying-manual-axes check and ``axis_names=`` for
partial-manual regions.  Older runtimes (0.4.x) ship the same machinery
as ``jax.experimental.shard_map.shard_map`` with a different spelling:
``check_rep=`` for the (equivalent) replication check and ``auto=`` —
the COMPLEMENT of ``axis_names`` over the mesh — for partial-manual.
This shim translates so kernel code is written once, against the modern
names.
"""

from typing import Optional

import jax


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` (new in jax 0.6); on older runtimes the
    classic spelling — a psum of 1 over the axis — constant-folds to the
    same value inside the traced program."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


try:  # jax >= 0.6: top-level export
    from jax import shard_map as _native_shard_map

    def shard_map(f, *, mesh=None, in_specs, out_specs, check_vma=True,
                  axis_names: Optional[frozenset] = None):
        kw = {}
        if mesh is not None:
            kw["mesh"] = mesh
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
        return _native_shard_map(f, in_specs=in_specs, out_specs=out_specs,
                                 check_vma=check_vma, **kw)

except ImportError:  # jax 0.4.x: experimental module
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def _ambient_mesh():
        # mesh=None means "the context mesh" on modern jax; the 0.4.x
        # equivalent is the `with Mesh(...):` thread-local
        from jax._src import mesh as _mesh_lib

        m = _mesh_lib.thread_resources.env.physical_mesh
        if m.empty:
            raise ValueError(
                "shard_map(mesh=None) needs an ambient mesh: wrap the "
                "call in `with Mesh(...):` (this jax predates context-"
                "mesh resolution)")
        return m

    def shard_map(f, *, mesh=None, in_specs, out_specs, check_vma=True,
                  axis_names: Optional[frozenset] = None):
        if mesh is None:
            mesh = _ambient_mesh()
        kw = {"check_rep": check_vma}
        if axis_names is not None:
            # partial-manual: modern names the MANUAL axes; 0.4.x names
            # the AUTO (non-manual) remainder
            kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _experimental_shard_map(f, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs, **kw)
