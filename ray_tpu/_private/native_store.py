"""ctypes binding for the native shared-memory arena store.

`NativeShmObjectStore` implements the exact interface of the file-per-object
`FileObjectStore` (shm_store.py) on top of the C++ arena
(ray_tpu/native/shm_arena.cc): one mmap-backed arena file per node session,
page-aligned payloads so each reader maps only its object, pid-validated
reader pins, and inline LRU eviction under memory pressure — the plasma
equivalent (reference: src/ray/object_manager/plasma/store.h) without a
store daemon or socket round-trips.

Objects too large for the arena overflow to the file-per-object store in
the same directory (the role plasma's fallback-allocation-to-disk plays,
reference: plasma/plasma_allocator.h fallback allocator).
"""

from __future__ import annotations

import ctypes
import logging
import mmap
import os
import weakref
from typing import List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

def DEFAULT_CAPACITY() -> int:
    # read at store-construction time so tests/daemons can size the arena
    # through the environment / _system_config (config.py flag table).
    # Unset: 30% of system memory like the reference's plasma sizing
    # (reference: ray_constants.py DEFAULT_OBJECT_STORE_MEMORY_PROPORTION),
    # clamped to [1 GiB, 64 GiB].  The arena file is sparse — untouched
    # capacity costs nothing.
    from .config import cfg

    configured = cfg().object_store_bytes
    if configured:
        return configured
    total = 0
    try:
        total = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        pass
    # The warm-first extent allocator (shm_arena.cc extent_alloc) keeps
    # the touched page window as small as the live set, so a generous
    # sparse arena costs nothing until used.
    return max(1 << 30, min(int(total * 0.3), 64 << 30))
N_ENTRIES = 16384  # power of two

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    from ray_tpu.native.build import load_library

    lib = load_library("shm_arena", ["shm_arena.cc"])
    lib.rt_arena_open.restype = ctypes.c_void_p
    lib.rt_arena_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_uint32]
    lib.rt_arena_close.argtypes = [ctypes.c_void_p]
    lib.rt_create.restype = ctypes.c_uint64
    lib.rt_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint64,
                              ctypes.POINTER(ctypes.c_int),
                              ctypes.c_uint32]
    lib.rt_set_primary.restype = ctypes.c_int
    lib.rt_set_primary.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
    lib.rt_get_flags.restype = ctypes.c_int64
    lib.rt_get_flags.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    for fn in ("rt_seal", "rt_abort", "rt_release", "rt_delete",
               "rt_contains"):
        f = getattr(lib, fn)
        f.restype = ctypes.c_int
        f.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_get.restype = ctypes.c_uint64
    lib.rt_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_size.restype = ctypes.c_int64
    lib.rt_size.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_list.restype = ctypes.c_uint64
    lib.rt_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_uint64]
    lib.rt_stats.argtypes = [ctypes.c_void_p] + [
        ctypes.POINTER(ctypes.c_uint64)] * 4
    lib.rt_memcpy.restype = None
    lib.rt_memcpy.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                              ctypes.c_uint64]
    _lib = lib
    return lib


def available() -> bool:
    try:
        _load()
        return True
    except Exception as e:  # toolchain missing → caller falls back
        logger.warning("native store unavailable: %s", e)
        return False


class NativeShmObjectStore:
    """Arena-backed store with file-per-object overflow."""

    def __init__(self, root: str, capacity: int = 0):
        from .shm_store import FileObjectStore

        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lib = _load()
        self._capacity = capacity or DEFAULT_CAPACITY()
        self._arena_path = os.path.join(root, "arena.shm")
        self._arena = self._lib.rt_arena_open(
            self._arena_path.encode(), self._capacity, N_ENTRIES)
        if not self._arena:
            raise RuntimeError(f"rt_arena_open failed for {self._arena_path}")
        self._fd = os.open(self._arena_path, os.O_RDWR)
        # One long-lived rw mapping of the whole arena for the write path:
        # a per-create mmap/munmap pays ~size/4KiB soft page-faults on every
        # put (the munmap drops the PTEs even though the shm pages stay in
        # the page cache), which halves large-put bandwidth. PTEs under a
        # persistent map survive across puts, so after warmup a put is one
        # memcpy. Readers keep per-object maps — their pin release is tied
        # to the mapping's lifetime (see _map_object).
        self._wmap: Optional[mmap.mmap] = None
        try:
            self._wmap = mmap.mmap(self._fd, 0)
        except (ValueError, OSError):
            pass  # fall back to per-create mappings
        self._overflow = FileObjectStore(root)
        # Shared with reader-pin finalizers: once closed, the arena handle
        # is gone and late releases must become no-ops (pins of a live pid
        # are reclaimed by dead-pid validation only at process exit — the
        # store is only closed at shutdown, so the leak window is nil).
        self._state = {"closed": False}

    # -- write path --------------------------------------------------------

    def _check_open(self):
        if self._state["closed"]:
            raise ValueError("object store is closed")

    PRIMARY = 1  # arena kFlagPrimary: unevictable until spilled

    WARM_ONLY = 1 << 30  # arena kFlagWarmOnly: fail rather than touch cold pages

    def create(self, object_id: str, meta: bytes,
               buffers: Sequence[memoryview], primary: bool = True,
               allow_overflow: bool = True,
               warm_only: bool = False) -> Optional[int]:
        """Write an object into the arena.  Returns its packed size, or
        None when allow_overflow=False and the arena has no room — or
        warm_only=True and only never-touched (cold) space fits — so the
        caller can free memory (e.g. flush deferred deletes) and retry."""
        from .shm_store import layout_size, pack_into

        self._check_open()
        size = layout_size(len(meta), [len(b) for b in buffers])
        oid = object_id.encode()
        err = ctypes.c_int(0)
        flags = self.PRIMARY if primary else 0
        if warm_only:
            flags |= self.WARM_ONLY
        off = self._lib.rt_create(self._arena, oid, size,
                                  ctypes.byref(err), flags)
        if err.value == 1:
            return size  # already created/sealed: objects are immutable
        if off == 0:
            if warm_only or not allow_overflow:
                return None
            # arena exhausted even after eviction → file overflow
            return self._overflow.create(object_id, meta, buffers)
        try:
            if self._wmap is not None and off + size <= len(self._wmap):
                dst = memoryview(self._wmap)[off:off + size]
                try:
                    self._pack_fast(dst, meta, buffers)
                finally:
                    dst.release()
            else:
                mm = mmap.mmap(self._fd, size, offset=off)
                try:
                    self._pack_fast(memoryview(mm), meta, buffers)
                finally:
                    mm.close()
        except BaseException:
            self._lib.rt_abort(self._arena, oid)
            raise
        self._lib.rt_seal(self._arena, oid)
        return size

    _GIL_FREE_COPY_MIN = 1 << 20  # below this, numpy/ctypes setup dominates

    def _pack_fast(self, dst: memoryview, meta: bytes,
                   buffers: Sequence[memoryview]) -> None:
        """pack_into, but large payload copies go through the native
        rt_memcpy — ctypes foreign calls release the GIL, so concurrent
        putters' copies run in parallel instead of serializing on the
        interpreter lock (a memoryview slice-assign holds the GIL for
        the whole memcpy).  The header layout is owned by
        shm_store.pack_header_into (shared with pack_into)."""
        import numpy as np

        from .shm_store import _pad, pack_header_into

        off = pack_header_into(dst, meta, [len(b) for b in buffers])
        dst_np = None
        for b in buffers:
            mv = b.cast("B") if isinstance(b, memoryview) else memoryview(b)
            n = len(mv)
            if n >= self._GIL_FREE_COPY_MIN:
                try:
                    src_np = np.frombuffer(mv, np.uint8)
                    if dst_np is None:
                        dst_np = np.frombuffer(dst, np.uint8)
                    self._lib.rt_memcpy(
                        ctypes.c_void_p(dst_np.ctypes.data + off),
                        ctypes.c_void_p(src_np.ctypes.data),
                        ctypes.c_uint64(n))
                    off = _pad(off + n)
                    continue
                except (ValueError, BufferError):
                    pass  # non-contiguous: plain slice-assign below
            dst[off:off + n] = mv
            off = _pad(off + n)

    def put_raw(self, object_id: str, data: bytes) -> int:
        # raw blobs are cache-like (no owner tracking them): evictable
        return self.create(object_id, b"", [memoryview(data)],
                           primary=False)

    # -- read path ---------------------------------------------------------

    def _map_object(self, object_id: str) -> Optional[memoryview]:
        """Pin + map one object; releases the pin when the mapping (and
        every buffer derived from it) is garbage-collected."""
        self._check_open()
        oid = object_id.encode()
        size = ctypes.c_uint64(0)
        off = self._lib.rt_get(self._arena, oid, ctypes.byref(size))
        if off == 0:
            return None
        if size.value == 0:
            # mmap(length=0) would map to EOF — leaking neighboring objects
            self._lib.rt_release(self._arena, oid)
            return memoryview(b"")
        mm = mmap.mmap(self._fd, size.value, offset=off,
                       prot=mmap.PROT_READ)
        lib, arena, state = self._lib, self._arena, self._state

        def _release():
            if state["closed"]:
                return
            try:
                lib.rt_release(arena, oid)
            except Exception:
                pass  # interpreter teardown

        weakref.finalize(mm, _release)
        return memoryview(mm)

    def contains(self, object_id: str) -> bool:
        self._check_open()
        if self._lib.rt_contains(self._arena, object_id.encode()):
            return True
        return self._overflow.contains(object_id)

    def get(self, object_id: str) -> Optional[Tuple[bytes, List[memoryview]]]:
        from .shm_store import unpack

        buf = self._map_object(object_id)
        if buf is None:
            return self._overflow.get(object_id)
        return unpack(buf)

    def get_raw(self, object_id: str) -> Optional[memoryview]:
        r = self.get(object_id)
        if r is None:
            return None
        _, bufs = r
        return bufs[0] if bufs else memoryview(b"")

    def read_bytes(self, object_id: str) -> Optional[bytes]:
        buf = self._map_object(object_id)
        if buf is None:
            return self._overflow.read_bytes(object_id)
        return bytes(buf)

    def write_bytes(self, object_id: str, data: bytes,
                    primary: bool = False) -> None:
        """Write a pre-packed object.  Non-primary by default: this is the
        path for pulled remote copies and spill restores, both of which
        remain recoverable elsewhere and so may be LRU-evicted."""
        self._check_open()
        oid = object_id.encode()
        err = ctypes.c_int(0)
        off = self._lib.rt_create(self._arena, oid, len(data),
                                  ctypes.byref(err),
                                  self.PRIMARY if primary else 0)
        if err.value == 1:
            return
        if off == 0:
            self._overflow.write_bytes(object_id, data)
            return
        mm = mmap.mmap(self._fd, max(len(data), 1), offset=off)
        try:
            mm[:len(data)] = data
        finally:
            mm.close()
        self._lib.rt_seal(self._arena, oid)

    # -- lifetime ----------------------------------------------------------

    def release(self, object_id: str) -> None:
        pass  # pins are owned by mappings (see _map_object)

    def set_primary(self, object_id: str, on: bool) -> bool:
        self._check_open()
        return self._lib.rt_set_primary(self._arena, object_id.encode(),
                                        1 if on else 0) == 0

    def is_primary(self, object_id: str) -> bool:
        self._check_open()
        flags = self._lib.rt_get_flags(self._arena, object_id.encode())
        if flags >= 0:
            return bool(flags & self.PRIMARY)
        # file-overflow objects hold the only copy of primary creates too;
        # treat unknown-to-arena as spillable
        return self._overflow.contains(object_id)

    def try_free(self, object_id: str) -> bool:
        """Delete only if the memory is actually reclaimed now (a pinned
        arena entry survives rt_delete with rc=1)."""
        self._check_open()
        if self._lib.rt_delete(self._arena, object_id.encode()) == 0:
            return True
        return self._overflow.delete(object_id)

    def delete(self, object_id: str) -> bool:
        self._check_open()
        rc = self._lib.rt_delete(self._arena, object_id.encode())
        dropped = rc >= 0
        if self._overflow.delete(object_id):
            dropped = True
        return dropped

    def size(self, object_id: str) -> Optional[int]:
        self._check_open()
        n = self._lib.rt_size(self._arena, object_id.encode())
        if n >= 0:
            return int(n)
        return self._overflow.size(object_id)

    def list_objects(self) -> List[str]:
        self._check_open()
        buflen = 1 << 20
        buf = ctypes.create_string_buffer(buflen)
        n = self._lib.rt_list(self._arena, buf, buflen)
        ids = buf.raw.split(b"\x00")[:n] if n else []
        out = [i.decode() for i in ids if i]
        for oid in self._overflow.list_objects():
            if oid != "arena.shm" and oid not in out:
                out.append(oid)
        return out

    def stats(self) -> dict:
        self._check_open()
        cap = ctypes.c_uint64(0)
        used = ctypes.c_uint64(0)
        nobj = ctypes.c_uint64(0)
        nevict = ctypes.c_uint64(0)
        self._lib.rt_stats(self._arena, ctypes.byref(cap),
                           ctypes.byref(used), ctypes.byref(nobj),
                           ctypes.byref(nevict))
        return {"capacity": cap.value, "used": used.value,
                "num_objects": nobj.value, "num_evictions": nevict.value}

    def wait_sealed(self, object_id: str, timeout: float) -> bool:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.contains(object_id):
                return True
            time.sleep(0.002)
        return self.contains(object_id)

    def close(self) -> None:
        if self._state["closed"]:
            return
        self._state["closed"] = True
        if self._wmap is not None:
            try:
                self._wmap.close()
            except (BufferError, ValueError):
                pass  # an exported slice outlives us; drop the ref instead
            self._wmap = None
        try:
            os.close(self._fd)
        except OSError:
            pass
        self._lib.rt_arena_close(self._arena)
        self._arena = None

    def destroy(self) -> None:
        self.close()
        self._overflow.destroy()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
