"""Flight-recorder primitives for the framed RPC layer.

Reference: src/ray/stats/metric_defs.cc + event_stats.h — the reference
instruments every gRPC handler with count/queueing/execution stats and a
per-handler "expected latency" warning threshold.  This module holds the
shared pieces: a fixed-bucket log-scale latency histogram cheap enough
for the dispatch hot path, the per-method stat record kept by
``protocol.Server`` and the per-handler latency *budget table* the
runtime warns against and ``ray_tpu.analysis`` promotes lock-held
blocking warnings with.

Everything here is stdlib-only and import-cycle-free: ``protocol.py``,
the analyzer and the bench harness all import it.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

# Log-scale bucket upper bounds in seconds (25us .. 10s + overflow).
# Fixed for every histogram so snapshots merge bucket-by-bucket.
BOUNDS_S = (
    25e-6, 50e-6, 100e-6, 250e-6, 500e-6,
    1e-3, 2.5e-3, 5e-3, 10e-3, 25e-3, 50e-3, 100e-3, 250e-3, 500e-3,
    1.0, 2.5, 5.0, 10.0,
)
BOUNDS_MS = tuple(round(b * 1e3, 3) for b in BOUNDS_S)


class LatencyHist:
    """Fixed-bucket latency histogram (seconds in, ms out).

    Not internally locked: the owner serializes writes (the Server's
    stats lock, or a single recording thread).
    """

    __slots__ = ("counts", "count", "sum_s", "max_s")

    def __init__(self):
        self.counts = [0] * (len(BOUNDS_S) + 1)  # +1 = overflow bucket
        self.count = 0
        self.sum_s = 0.0
        self.max_s = 0.0

    def observe(self, dt_s: float) -> None:
        if dt_s < 0.0:
            dt_s = 0.0
        self.count += 1
        self.sum_s += dt_s
        if dt_s > self.max_s:
            self.max_s = dt_s
        for i, b in enumerate(BOUNDS_S):
            if dt_s <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def merge(self, other: "LatencyHist") -> None:
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum_s += other.sum_s
        if other.max_s > self.max_s:
            self.max_s = other.max_s

    def percentile_s(self, q: float) -> float:
        """Upper bucket bound at quantile q (0..1); max_s for overflow."""
        if self.count == 0:
            return 0.0
        target = max(1, int(q * self.count + 0.5))
        acc = 0
        for i, c in enumerate(self.counts[:-1]):
            acc += c
            if acc >= target:
                return BOUNDS_S[i]
        return self.max_s

    def snapshot(self) -> Dict[str, object]:
        ms = 1e3
        return {
            "count": self.count,
            "sum_ms": round(self.sum_s * ms, 3),
            "max_ms": round(self.max_s * ms, 3),
            "p50_ms": round(self.percentile_s(0.50) * ms, 3),
            "p90_ms": round(self.percentile_s(0.90) * ms, 3),
            "p99_ms": round(self.percentile_s(0.99) * ms, 3),
            "buckets": list(self.counts),
        }


class MethodStats:
    """Per-RPC-method server-side record (see protocol.Server)."""

    __slots__ = ("count", "errors", "inflight", "bytes_in", "bytes_out",
                 "replays", "budget_ms", "budget_exceeded", "last_warn",
                 "qwait", "handle")

    def __init__(self, budget_ms: Optional[float] = None):
        self.count = 0
        self.errors = 0
        self.inflight = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.replays = 0
        self.budget_ms = budget_ms
        self.budget_exceeded = 0
        self.last_warn = 0.0
        self.qwait = LatencyHist()    # recv -> dispatch start
        self.handle = LatencyHist()   # dispatch start -> reply sent

    def snapshot(self) -> Dict[str, object]:
        h = self.handle
        out = {
            # legacy surface (pre-flight-recorder consumers)
            "count": self.count,
            "total_s": round(h.sum_s, 6),
            "mean_us": round(h.sum_s / h.count * 1e6, 1) if h.count else 0.0,
            "max_us": round(h.max_s * 1e6, 1),
            # flight recorder
            "errors": self.errors,
            "in_flight": self.inflight,
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
            "replays": self.replays,
            "queue_ms": self.qwait.snapshot(),
            "handle_ms": h.snapshot(),
        }
        if self.budget_ms is not None:
            out["budget_ms"] = self.budget_ms
            out["budget_exceeded"] = self.budget_exceeded
        return out


# ---------------------------------------------------------------------------
# Per-handler latency budgets (milliseconds), seeded from bench.py
# --control-only measurements on a shared 8-vCPU host (generous ~10x
# headroom over observed p99 so shared-host jitter does not page anyone).
#
# A budgeted handler runs ON the server event loop and stalls every
# connection while it executes: exceeding the budget logs a warning at
# runtime, and `ray-tpu analyze` PROMOTES a lock-held-across-blocking-call
# warning inside a budgeted handler to a gating finding
# (`budget-held-blocking`).  Long-poll / admission handlers whose latency
# is dominated by legitimate waiting (wait_actor_alive, create_actor,
# create_pg, remove_pg, request_lease*) are deliberately absent: a wall
# budget is meaningless for them and their known lock-held warnings stay
# baselined warnings.
# ---------------------------------------------------------------------------

HANDLER_BUDGETS_MS = {
    # shared
    "ping": 5.0,
    "rpc_stats": 50.0,
    # control plane
    "kv_put": 25.0,
    "kv_get": 10.0,
    "kv_del": 10.0,
    "kv_keys": 25.0,
    "kv_exists": 5.0,
    "register_node": 100.0,
    "unregister_node": 50.0,
    "heartbeat": 10.0,
    "report_draining": 10.0,
    "report_quarantine": 10.0,
    "get_nodes": 25.0,
    "pick_node": 10.0,
    "pick_nodes": 25.0,
    "register_function": 50.0,
    "get_function": 25.0,
    "register_job": 25.0,
    "get_actor": 10.0,
    "get_actor_spec": 10.0,
    "list_actors": 50.0,
    "actor_ready": 10.0,
    "actor_failed": 25.0,
    "subscribe": 10.0,
    "publish": 25.0,
    "get_pg": 10.0,
    "list_pgs": 50.0,
    "cluster_resources": 25.0,
    "state_dump": 250.0,
    "report_task_events": 50.0,
    "list_events": 50.0,
    "report_event": 10.0,
    "control_stats": 50.0,
    # raylet
    "register_worker": 25.0,
    "return_lease": 10.0,
    "cancel_lease_requests": 10.0,
    "task_blocked": 10.0,
    "task_unblocked": 10.0,
    "kill_actor_worker": 50.0,
    "prepare_bundle": 100.0,
    "commit_bundle": 50.0,
    "release_bundle": 50.0,
    "fetch_object": 100.0,
    "delete_objects": 50.0,
    "store_stats": 25.0,
    "node_info": 25.0,
    "list_leases": 50.0,
    "list_workers": 25.0,
    "list_logs": 50.0,
    "read_log": 100.0,
    "pending_demands": 25.0,
}


def budget_ms(method: str) -> Optional[float]:
    return HANDLER_BUDGETS_MS.get(method)


# ---------------------------------------------------------------------------
# Process-local pubsub delivery aggregator.  The publisher stamps a
# wall-clock send time on the wire (frame meta "ts"); every subscribing
# Client in this process records publish->deliver latency here, keyed by
# topic.  The swarm bench and raylet-resident subscribers read it back
# via pubsub_delivery_snapshot().
# ---------------------------------------------------------------------------

_pubsub_lock = threading.Lock()
_pubsub: Dict[str, LatencyHist] = {}


def record_pubsub_delivery(topic: str, latency_s: float) -> None:
    with _pubsub_lock:
        h = _pubsub.get(topic)
        if h is None:
            h = _pubsub[topic] = LatencyHist()
        h.observe(latency_s)


def pubsub_delivery_snapshot(reset: bool = False) -> Dict[str, Dict]:
    with _pubsub_lock:
        out = {t: h.snapshot() for t, h in _pubsub.items()}
        if reset:
            _pubsub.clear()
    return out


def merge_client_stats(agg: Dict[str, List[int]],
                       raw: Dict[str, List[int]]) -> None:
    """Accumulate one Client.stats_raw() into an aggregate (in place)."""
    for m, s in raw.items():
        a = agg.setdefault(m, [0] * len(s))
        for i, v in enumerate(s):
            a[i] += v
