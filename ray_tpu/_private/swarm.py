"""Virtual-node swarm: hundreds of in-process raylets against one REAL
control daemon.

The control-plane flight recorder needs load to record: this module
spins up N ``VirtualNode``s — each a real ``protocol.Server`` granting
leases from a fake CPU pool plus a real ``protocol.Client`` that
registers, heartbeats (versioned delta sync) and subscribes to a swarm
pubsub topic — and drives the three control-plane hot paths the bench
reports on:

* heartbeat round-trip latency (client-observed, via ``call_cb``),
* pick_node -> request_lease -> return_lease grant cycles,
* pubsub publish -> deliver fan-out (wire-stamped, aggregated by
  ``rpc_stats.record_pubsub_delivery`` in the subscribing clients).

Everything runs in one process except the control daemon itself
(``bootstrap.Cluster.start_control`` subprocess), so the numbers isolate
the control plane: no workers, no object store, no scheduler churn.
Used by ``bench.py --control-only`` (BENCH_CONTROL.json) and the tier-1
swarm smoke test at N=50.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from . import rpc_stats
from .protocol import Client, Server

logger = logging.getLogger(__name__)

SWARM_TOPIC = "swarm"


class VirtualNode:
    """An in-process stand-in for a raylet: real RPC server + control
    client, fake everything else.  Lease grants draw from a plain CPU
    counter; exhaustion replies ``ok=False`` instead of queueing (the
    swarm driver returns leases fast enough that control-side optimistic
    reservation keeps picks and capacity in step)."""

    def __init__(self, index: int, control_addr: Tuple[str, int],
                 cpus: float = 8.0):
        self.node_id = f"vnode-{index:04d}"
        self._lock = threading.Lock()
        self._cpus = float(cpus)
        self._avail = float(cpus)
        self._version = 1          # bumped on every grant/return
        self._sent_version = 0     # last version shipped in a heartbeat
        self._next_lease = 0
        self._leases: Dict[str, float] = {}
        self.hb_errors = 0

        s = Server(name=f"swarm-{self.node_id}")
        s.handle("ping", lambda c, p: {"ok": True})
        s.handle("request_lease", self.h_request_lease)
        s.handle("request_leases", self.h_request_leases)
        s.handle("return_lease", self.h_return_lease)
        s.start()
        self.server = s
        self.control = Client(control_addr, name=self.node_id)

    # -- raylet-side handlers ----------------------------------------------

    def _grant_locked(self, need: float) -> Optional[str]:
        if need > self._avail:
            return None
        self._avail -= need
        self._version += 1
        lease_id = f"{self.node_id}-l{self._next_lease}"
        self._next_lease += 1
        self._leases[lease_id] = need
        return lease_id

    def h_request_lease(self, conn, p):
        need = float((p.get("resources") or {}).get("CPU", 1))
        with self._lock:
            lid = self._grant_locked(need)
        if lid is None:
            return {"ok": False, "reason": "exhausted"}
        return {"ok": True, "lease_id": lid, "node_id": self.node_id}

    def h_request_leases(self, conn, p):
        need = float((p.get("resources") or {}).get("CPU", 1))
        count = max(1, int(p.get("count", 1)))
        grants = []
        with self._lock:
            for _ in range(count):
                lid = self._grant_locked(need)
                if lid is None:
                    break
                grants.append({"lease_id": lid, "node_id": self.node_id})
        if not grants:
            return {"ok": False, "reason": "exhausted"}
        return {"ok": True, "grants": grants}

    def h_return_lease(self, conn, p):
        with self._lock:
            need = self._leases.pop(p.get("lease_id"), None)
            if need is not None:
                self._avail += need
                self._version += 1
        return {"ok": True}

    # -- control-side traffic ----------------------------------------------

    def register(self) -> None:
        self.control.call("register_node", {
            "node_id": self.node_id, "addr": self.server.addr,
            "resources": {"CPU": self._cpus},
            "labels": {"swarm": "1"}}, timeout=30.0)
        self.control.call("subscribe", {"topics": [SWARM_TOPIC]},
                          timeout=30.0)

    def heartbeat(self, hist: rpc_stats.LatencyHist,
                  hist_lock: threading.Lock) -> None:
        """One non-blocking heartbeat; the reply callback records the
        round trip.  Availability rides along only when it changed since
        the last send (the versioned delta protocol, ray_syncer-style)."""
        payload: Dict[str, Any] = {"node_id": self.node_id}
        with self._lock:
            if self._version != self._sent_version:
                payload["available"] = {"CPU": self._avail}
                payload["avail_version"] = self._version
                self._sent_version = self._version
        t0 = time.perf_counter()

        def cb(reply, exc):
            if exc is not None:
                self.hb_errors += 1
                return
            if isinstance(reply, dict) and reply.get("resync"):
                # control's optimistic pick_node reservations drifted its
                # view; force ground truth onto the next beat even though
                # our local version didn't change (delta-sync resync)
                with self._lock:
                    self._sent_version = 0
            dt = time.perf_counter() - t0
            with hist_lock:
                hist.observe(dt)

        try:
            self.control.call_cb("heartbeat", payload, cb)
        except Exception:
            self.hb_errors += 1

    def close(self) -> None:
        try:
            self.control.close()
        finally:
            self.server.stop()


class Swarm:
    """N virtual nodes + the driver loops that exercise the control."""

    def __init__(self, control_addr: Tuple[str, int], n_nodes: int,
                 cpus_per_node: float = 8.0,
                 hb_interval_s: float = 0.5):
        self.control_addr = tuple(control_addr)
        self.n_nodes = n_nodes
        self.cpus_per_node = cpus_per_node
        self.hb_interval_s = hb_interval_s
        self.nodes: List[VirtualNode] = []
        self._stop = threading.Event()
        self._hb_lock = threading.Lock()
        self._hb_hist = rpc_stats.LatencyHist()
        self._pacer: Optional[threading.Thread] = None

    def start(self) -> None:
        self.nodes = [VirtualNode(i, self.control_addr,
                                  cpus=self.cpus_per_node)
                      for i in range(self.n_nodes)]
        # parallel registration: 500 serial connect+register round trips
        # would dominate small-duration runs
        with ThreadPoolExecutor(max_workers=16) as ex:
            list(ex.map(lambda vn: vn.register(), self.nodes))
        self._pacer = threading.Thread(target=self._pace_loop,
                                       name="swarm-heartbeat", daemon=True)
        self._pacer.start()

    def _pace_loop(self) -> None:
        # one pacer thread for the whole swarm: sends are non-blocking
        # (call_cb enqueues), replies land on each client's reader thread
        while not self._stop.is_set():
            t_next = time.perf_counter() + self.hb_interval_s
            for vn in self.nodes:
                if self._stop.is_set():
                    return
                vn.heartbeat(self._hb_hist, self._hb_lock)
            delay = t_next - time.perf_counter()
            if delay > 0:
                self._stop.wait(delay)

    def heartbeat_snapshot(self) -> Dict[str, Any]:
        with self._hb_lock:
            snap = self._hb_hist.snapshot()
        snap["errors"] = sum(vn.hb_errors for vn in self.nodes)
        return snap

    def run_leases(self, duration_s: float, threads: int = 4) -> Dict[str, Any]:
        """Full pick_node -> request_lease -> return_lease cycles from
        `threads` concurrent drivers for `duration_s`; returns the grant
        rate the control plane + virtual raylets sustained."""
        stop = threading.Event()
        grants = [0] * threads
        misses = [0] * threads

        def driver(t: int):
            probe = Client(self.control_addr, name=f"swarm-lease-{t}")
            conns: Dict[Tuple[str, int], Client] = {}
            try:
                while not stop.is_set():
                    pick = probe.call("pick_node",
                                      {"resources": {"CPU": 1}},
                                      timeout=10.0)
                    if pick is None:
                        misses[t] += 1
                        time.sleep(0.005)
                        continue
                    addr = tuple(pick["addr"])
                    cli = conns.get(addr)
                    if cli is None:
                        cli = conns[addr] = Client(
                            addr, name=f"swarm-lease-{t}-vn")
                    r = cli.call("request_lease",
                                 {"resources": {"CPU": 1}}, timeout=10.0)
                    if r and r.get("ok"):
                        grants[t] += 1
                        cli.call("return_lease",
                                 {"lease_id": r["lease_id"]}, timeout=10.0)
                    else:
                        misses[t] += 1
            finally:
                probe.close()
                for c in conns.values():
                    c.close()

        ts = [threading.Thread(target=driver, args=(t,), daemon=True)
              for t in range(threads)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in ts:
            t.join(timeout=10.0)
        wall = time.perf_counter() - t0
        total = sum(grants)
        return {"grants": total, "misses": sum(misses),
                "grants_per_s": round(total / wall, 1),
                "threads": threads}

    def run_pubsub(self, n_msgs: int = 20,
                   interval_s: float = 0.02) -> Dict[str, Any]:
        """Publish n_msgs to the swarm topic and wait for the full
        fan-out (n_msgs x n_nodes deliveries), then report the
        publish->deliver latency the subscribing clients recorded."""
        rpc_stats.pubsub_delivery_snapshot(reset=True)
        probe = Client(self.control_addr, name="swarm-pub")
        try:
            for i in range(n_msgs):
                probe.call("publish", {
                    "topic": SWARM_TOPIC,
                    "payload": {"seq": i, "pad": "x" * 128}}, timeout=10.0)
                time.sleep(interval_s)
            expected = n_msgs * self.n_nodes
            deadline = time.monotonic() + 30.0
            snap = {}
            while time.monotonic() < deadline:
                snap = rpc_stats.pubsub_delivery_snapshot().get(
                    SWARM_TOPIC, {})
                if snap.get("count", 0) >= expected:
                    break
                time.sleep(0.05)
            snap = dict(snap)
            snap["expected"] = expected
            return snap
        finally:
            probe.close()

    def control_stats(self) -> Dict[str, Any]:
        probe = Client(self.control_addr, name="swarm-stats")
        try:
            return probe.call("control_stats", {}, timeout=30.0)
        finally:
            probe.close()

    def close(self) -> None:
        self._stop.set()
        if self._pacer is not None:
            self._pacer.join(timeout=5.0)
        with ThreadPoolExecutor(max_workers=16) as ex:
            list(ex.map(lambda vn: vn.close(), self.nodes))
        self.nodes = []


def run_swarm_bench(n_nodes: int, *, hb_interval_s: float = 0.5,
                    settle_s: float = 1.0, lease_secs: float = 4.0,
                    lease_threads: int = 4, pub_msgs: int = 20,
                    control_addr: Optional[Tuple[str, int]] = None
                    ) -> Dict[str, Any]:
    """One bench row: start a fresh control daemon (unless given one),
    run a swarm of `n_nodes` against it, return the flight-recorder
    numbers.  Fresh daemon per N so dead prior-N nodes don't charge
    death-detection work to the next N."""
    cluster = None
    if control_addr is None:
        from .bootstrap import Cluster

        cluster = Cluster()
        control_addr = cluster.start_control()
    swarm = Swarm(control_addr, n_nodes, hb_interval_s=hb_interval_s)
    try:
        swarm.start()
        time.sleep(settle_s)
        leases = swarm.run_leases(lease_secs, threads=lease_threads)
        pubsub = swarm.run_pubsub(n_msgs=pub_msgs)
        hb = swarm.heartbeat_snapshot()
        cs = swarm.control_stats()
        handlers = cs.get("handlers") or {}
        loop = cs.get("loop") or {}
        lag = loop.get("lag_ms") or {}
        row = {
            "n_nodes": n_nodes,
            "hb_interval_s": hb_interval_s,
            "heartbeat_ms_p50": hb.get("p50_ms", 0.0),
            "heartbeat_ms_p99": hb.get("p99_ms", 0.0),
            "heartbeat_count": hb.get("count", 0),
            "heartbeat_errors": hb.get("errors", 0),
            "lease_grants_per_s": leases["grants_per_s"],
            "lease_grants": leases["grants"],
            "lease_misses": leases["misses"],
            "pubsub_fanout_ms_p50": pubsub.get("p50_ms", 0.0),
            "pubsub_fanout_ms_p99": pubsub.get("p99_ms", 0.0),
            "pubsub_delivered": pubsub.get("count", 0),
            "pubsub_expected": pubsub.get("expected", 0),
            "control_loop_lag_ms_p99": lag.get("p99_ms", 0.0),
            "handler_p99_ms": {
                m: (handlers[m].get("handle_ms") or {}).get("p99_ms", 0.0)
                for m in ("heartbeat", "pick_node", "publish",
                          "register_node")
                if m in handlers},
        }
        return row
    finally:
        swarm.close()
        if cluster is not None:
            cluster.shutdown()
