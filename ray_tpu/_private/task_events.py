"""Buffered export of task lifecycle + profile events to the control plane.

Analog of the reference's TaskEventBuffer (reference:
src/ray/core_worker/task_event_buffer.h:220): every task submission and
execution transition is recorded locally and flushed in batches to the
control plane's task-event manager (reference: GcsTaskManager,
src/ray/gcs/gcs_server/gcs_task_manager.h), which the state API
(`ray_tpu.util.state`) and the Chrome-trace timeline read back.

States follow the reference's task lifecycle (common.proto TaskStatus):
PENDING_ARGS_AVAIL -> SUBMITTED_TO_WORKER -> RUNNING -> FINISHED | FAILED.
Profile events (named spans inside a task) feed the timeline view
(reference: `ray timeline` -> chrome://tracing).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Deque, Dict, Optional

FLUSH_INTERVAL_S = 1.0
MAX_BUFFERED = 10_000  # drop-oldest beyond this (reference: task_events_max_buffer_size)
# submit-path ring -> event conversion per flush window: bounds the dict
# building a 100k-task burst would otherwise pay inside one flush tick
SUBMIT_DRAIN_MAX = 5_000


class TaskEventBuffer:
    """Thread-safe accumulator; a daemon thread flushes to the control plane."""

    def __init__(self, control_client, *, worker_id: str = "",
                 node_id: str = "", job_id: str = "",
                 transport=None):
        self._client = control_client
        # optional transport override: fn(payload) sending the batch
        # somewhere other than the direct control call.  Workers pass a
        # raylet-relay notify here so each node makes ONE control write
        # per flush window instead of one per worker (satellite of
        # ROADMAP item 5's per-node batching direction).
        self._transport = transport
        self._worker_id = worker_id
        self._node_id = node_id
        self._job_id = job_id
        self._flushed_batches = 0
        self._flushed_events = 0
        self._lock = threading.Lock()
        # deque, NOT list: drop-oldest at capacity must stay O(1) —
        # list.pop(0) shifts the whole buffer per append once saturated,
        # which throttled 100k-task submission bursts ~14x (every
        # submission records events; found by the scalability envelope)
        self._events: Deque[Dict[str, Any]] = collections.deque(
            maxlen=MAX_BUFFERED)
        self._dropped = 0
        # submit-path ring: the owner's .remote() hot loop appends bare
        # tuples here (no dict build, no per-call time formatting beyond
        # one clock read); the flush thread converts them to full status
        # events off the hot path, rate-limited per window
        self._submit_ring: Deque[tuple] = collections.deque(
            maxlen=MAX_BUFFERED)
        self._submit_dropped = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._flush_loop,
                                        name="task-events-flush", daemon=True)
        self._thread.start()

    # -- recording ---------------------------------------------------------

    def record_status(self, task_id: str, state: str, *,
                      name: str = "", actor_id: Optional[str] = None,
                      error: Optional[str] = None,
                      extra: Optional[Dict[str, Any]] = None):
        # per-process constants (job/node/worker) ride once per batch as
        # the flush header, not per event — the control merges them back
        ev = {
            "kind": "status",
            "task_id": task_id,
            "state": state,
            "name": name,
            "actor_id": actor_id,
            "ts": time.time(),
        }
        if error:
            ev["error"] = error[:2000]
        if extra:
            ev.update(extra)
        self._append(ev)

    def record_submit(self, task_id: str, name: str, type_: str,
                      actor_id: Optional[str] = None):
        """Hot-path submission record (state PENDING_ARGS_AVAIL).  A bare
        tuple append into a bounded ring; the flush loop builds the event
        dict.  The deque append is atomic under the GIL, so no lock is
        taken here — the full/drop check races benignly (the counter is a
        metric, the maxlen deque enforces the bound regardless)."""
        ring = self._submit_ring
        if len(ring) == ring.maxlen:
            self._submit_dropped += 1  # maxlen evicts the oldest
        ring.append((task_id, name, type_, actor_id, time.time()))

    def _drain_submit_ring(self):
        """Convert up to SUBMIT_DRAIN_MAX staged submissions into status
        events (called from the flush thread).  Anything beyond the rate
        limit stays ringed for the next window; sustained overflow falls
        off the ring's tail into the dropped counter."""
        ring = self._submit_ring
        for _ in range(SUBMIT_DRAIN_MAX):
            try:
                task_id, name, type_, actor_id, ts = ring.popleft()
            except IndexError:
                break
            self._append({
                "kind": "status",
                "task_id": task_id,
                "state": "PENDING_ARGS_AVAIL",
                "name": name,
                "actor_id": actor_id,
                "ts": ts,
                "type": type_,
            })

    def record_profile(self, task_id: str, event_name: str,
                       start_ts: float, end_ts: float,
                       extra: Optional[Dict[str, Any]] = None):
        ev = {
            "kind": "profile",
            "task_id": task_id,
            "event_name": event_name,
            "start_ts": start_ts,
            "end_ts": end_ts,
        }
        if extra:
            ev.update(extra)
        self._append(ev)

    def _append(self, ev: Dict[str, Any]):
        with self._lock:
            if len(self._events) == MAX_BUFFERED:
                self._dropped += 1   # maxlen evicts the oldest on append
            self._events.append(ev)

    # -- flushing ----------------------------------------------------------

    def _flush_loop(self):
        while not self._stop.wait(FLUSH_INTERVAL_S):
            self.flush()

    def flush(self):
        self._drain_submit_ring()
        with self._lock:
            if not self._events and not self._submit_dropped:
                return
            batch = list(self._events)
            self._events.clear()
            dropped = self._dropped + self._submit_dropped
            self._dropped = 0
            self._submit_dropped = 0
            if not batch and not dropped:
                return
        payload = {"events": batch, "dropped": dropped,
                   "common": {"job_id": self._job_id,
                              "node_id": self._node_id,
                              "worker_id": self._worker_id}}
        try:
            if self._transport is not None:
                self._transport(payload)
            else:
                self._client.call("report_task_events", payload,
                                  timeout=5.0)
            with self._lock:
                self._flushed_batches += 1
                self._flushed_events += len(batch)
        except Exception:
            # control plane unreachable: re-queue (bounded) so a blip
            # doesn't lose the whole window; anything truncated off the
            # front counts as dropped, and the unsent dropped-count is
            # restored so it reaches control on the next success
            with self._lock:
                merged = batch + list(self._events)
                cut = max(0, len(merged) - MAX_BUFFERED)
                self._events = collections.deque(merged[cut:],
                                                 maxlen=MAX_BUFFERED)
                self._dropped += dropped + cut

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "buffered": len(self._events) + len(self._submit_ring),
                "flushed_batches": self._flushed_batches,
                "flushed_events": self._flushed_events,
                "dropped": self._dropped + self._submit_dropped,
            }

    def stop(self):
        self._stop.set()
        self.flush()


class _NullBuffer:
    """No-op stand-in before init / after shutdown."""

    def record_status(self, *a, **k):
        pass

    def record_submit(self, *a, **k):
        pass

    def record_profile(self, *a, **k):
        pass

    def flush(self):
        pass

    def stats(self):
        return {"buffered": 0, "flushed_batches": 0,
                "flushed_events": 0, "dropped": 0}

    def stop(self):
        pass


NULL_BUFFER = _NullBuffer()
