"""Datasources: how Datasets begin and end.

Reference: python/ray/data/datasource/ (Datasource, ReadTask) and
python/ray/data/read_api.py:334 read_datasource.  A Datasource produces
``ReadTask``s — serializable thunks that each yield one or more blocks on a
worker.  Writes are map tasks that persist blocks and return paths.
"""

from __future__ import annotations

import glob as _glob
import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from .block import Block, BlockMetadata, VALUE_COL, rows_to_block


@dataclass
class ReadTask:
    """A serializable unit of reading; runs on a worker and yields blocks."""

    read_fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata  # estimate (rows may be None-ish / approximate)

    def __call__(self) -> Iterable[Block]:
        return self.read_fn()


class Datasource:
    """Base datasource (reference: python/ray/data/datasource/datasource.py)."""

    def get_name(self) -> str:
        return type(self).__name__.replace("Datasource", "")

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError


class RangeDatasource(Datasource):
    def __init__(self, n: int, *, tensor_shape: Optional[tuple] = None):
        self._n = n
        self._tensor_shape = tensor_shape

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = self._n
        parallelism = max(1, min(parallelism, n or 1))
        tasks = []
        chunk = (n + parallelism - 1) // parallelism if n else 0
        shape = self._tensor_shape
        for start in range(0, n, max(chunk, 1)):
            end = min(start + chunk, n)

            def read(start=start, end=end):
                ids = np.arange(start, end, dtype=np.int64)
                if shape:
                    size = int(np.prod(shape))
                    data = (ids[:, None] * size
                            + np.arange(size, dtype=np.int64)[None, :])
                    batch = {"data": data.reshape((end - start,) + shape)}
                else:
                    batch = {"id": ids}
                from .block import batch_to_block

                yield batch_to_block(batch)

            meta = BlockMetadata(num_rows=end - start,
                                 size_bytes=(end - start) * 8)
            tasks.append(ReadTask(read, meta))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self._items
        n = len(items)
        parallelism = max(1, min(parallelism, n or 1))
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks = []
        for start in range(0, n, max(chunk, 1)):
            part = items[start:start + chunk]

            def read(part=part):
                yield rows_to_block(part)

            meta = BlockMetadata(num_rows=len(part), size_bytes=0)
            tasks.append(ReadTask(read, meta))
        return tasks


class BlocksDatasource(Datasource):
    """From already-materialized in-memory blocks (from_pandas/arrow/numpy)."""

    def __init__(self, blocks: List[Block]):
        self._blocks = blocks

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for b in self._blocks:
            def read(b=b):
                yield b

            tasks.append(ReadTask(read, BlockMetadata(
                num_rows=b.num_rows, size_bytes=b.nbytes, schema=b.schema)))
        return tasks


def _expand_paths(paths, suffixes: Optional[List[str]] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                for f in sorted(files):
                    out.append(os.path.join(root, f))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if suffixes:
        out = [p for p in out
               if any(p.endswith(s) for s in suffixes)] or out
    if not out:
        raise FileNotFoundError(f"no input files found for {paths!r}")
    return out


class FileBasedDatasource(Datasource):
    """One-or-more files per read task (reference:
    python/ray/data/datasource/file_based_datasource.py)."""

    _suffixes: Optional[List[str]] = None

    def __init__(self, paths, **reader_args):
        self._paths = _expand_paths(paths, self._suffixes)
        self._reader_args = reader_args

    def _read_file(self, path: str, **kwargs) -> Block:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        paths = self._paths
        parallelism = max(1, min(parallelism, len(paths)))
        groups: List[List[str]] = [[] for _ in range(parallelism)]
        for i, p in enumerate(paths):
            groups[i % parallelism].append(p)
        read_file = self._read_file
        args = self._reader_args
        tasks = []
        for group in groups:
            if not group:
                continue

            def read(group=group):
                for p in group:
                    yield read_file(p, **args)

            est = sum(os.path.getsize(p) for p in group
                      if os.path.exists(p))
            tasks.append(ReadTask(read, BlockMetadata(
                num_rows=0, size_bytes=est, input_files=group)))
        return tasks


class ParquetDatasource(FileBasedDatasource):
    _suffixes = [".parquet"]

    def _read_file(self, path: str, columns=None, **kw) -> Block:
        import pyarrow.parquet as pq

        return pq.read_table(path, columns=columns)


class CSVDatasource(FileBasedDatasource):
    _suffixes = [".csv"]

    def _read_file(self, path: str, **kw) -> Block:
        import pyarrow.csv as pcsv

        return pcsv.read_csv(path)


class JSONDatasource(FileBasedDatasource):
    _suffixes = [".json", ".jsonl"]

    def _read_file(self, path: str, **kw) -> Block:
        import pyarrow.json as pjson

        return pjson.read_json(path)


class TextDatasource(FileBasedDatasource):
    def _read_file(self, path: str, encoding="utf-8", drop_empty_lines=True,
                   **kw) -> Block:
        with open(path, "r", encoding=encoding) as f:
            lines = f.read().split("\n")
        if drop_empty_lines:
            lines = [ln for ln in lines if ln.strip()]
        return pa.table({"text": lines})


class BinaryDatasource(FileBasedDatasource):
    def _read_file(self, path: str, include_paths=False, **kw) -> Block:
        with open(path, "rb") as f:
            data = f.read()
        cols = {"bytes": [data]}
        if include_paths:
            cols["path"] = [path]
        return pa.table(cols)


class NumpyDatasource(FileBasedDatasource):
    _suffixes = [".npy"]

    def _read_file(self, path: str, **kw) -> Block:
        from .block import batch_to_block

        return batch_to_block({"data": np.load(path)})


# ---------------------------------------------------------------------------
# Writers (run inside map tasks; reference: file_datasink.py)

def write_block(block: Block, path: str, file_format: str,
                **writer_args) -> str:
    os.makedirs(path, exist_ok=True)
    fname = os.path.join(path, f"{uuid.uuid4().hex[:12]}.{file_format}")
    if file_format == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(block, fname, **writer_args)
    elif file_format == "csv":
        import pyarrow.csv as pcsv

        pcsv.write_csv(block, fname)
    elif file_format == "json":
        df = block.to_pandas()
        df.to_json(fname, orient="records", lines=True)
    elif file_format == "npy":
        from .block import BlockAccessor

        cols = BlockAccessor(block).to_numpy()
        if len(cols) == 1:
            np.save(fname, next(iter(cols.values())))
        else:
            np.save(fname, cols, allow_pickle=True)
    else:
        raise ValueError(f"unknown write format {file_format}")
    return fname
