"""Datasources: how Datasets begin and end.

Reference: python/ray/data/datasource/ (Datasource, ReadTask) and
python/ray/data/read_api.py:334 read_datasource.  A Datasource produces
``ReadTask``s — serializable thunks that each yield one or more blocks on a
worker.  Writes are map tasks that persist blocks and return paths.
"""

from __future__ import annotations

import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from .block import Block, BlockMetadata, VALUE_COL, rows_to_block


@dataclass
class ReadTask:
    """A serializable unit of reading; runs on a worker and yields blocks."""

    read_fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata  # estimate (rows may be None-ish / approximate)

    def __call__(self) -> Iterable[Block]:
        return self.read_fn()


class Datasource:
    """Base datasource (reference: python/ray/data/datasource/datasource.py)."""

    def get_name(self) -> str:
        return type(self).__name__.replace("Datasource", "")

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def plan_row_count(self) -> Optional[int]:
        """EXACT total row count known without executing any read, or
        None (reference: parquet metadata makes `ds.count()` an O(files)
        footer scan instead of a full read).  Only return a number that
        is guaranteed exact — Dataset.count() trusts it."""
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError


class RangeDatasource(Datasource):
    def __init__(self, n: int, *, tensor_shape: Optional[tuple] = None):
        self._n = n
        self._tensor_shape = tensor_shape

    def plan_row_count(self) -> Optional[int]:
        return self._n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = self._n
        parallelism = max(1, min(parallelism, n or 1))
        tasks = []
        chunk = (n + parallelism - 1) // parallelism if n else 0
        shape = self._tensor_shape
        for start in range(0, n, max(chunk, 1)):
            end = min(start + chunk, n)

            def read(start=start, end=end):
                ids = np.arange(start, end, dtype=np.int64)
                if shape:
                    size = int(np.prod(shape))
                    data = (ids[:, None] * size
                            + np.arange(size, dtype=np.int64)[None, :])
                    batch = {"data": data.reshape((end - start,) + shape)}
                else:
                    batch = {"id": ids}
                from .block import batch_to_block

                yield batch_to_block(batch)

            meta = BlockMetadata(num_rows=end - start,
                                 size_bytes=(end - start) * 8)
            tasks.append(ReadTask(read, meta))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = list(items)

    def plan_row_count(self) -> Optional[int]:
        return len(self._items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self._items
        n = len(items)
        parallelism = max(1, min(parallelism, n or 1))
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks = []
        for start in range(0, n, max(chunk, 1)):
            part = items[start:start + chunk]

            def read(part=part):
                yield rows_to_block(part)

            meta = BlockMetadata(num_rows=len(part), size_bytes=0)
            tasks.append(ReadTask(read, meta))
        return tasks


class BlocksDatasource(Datasource):
    """From already-materialized in-memory blocks (from_pandas/arrow/numpy)."""

    def __init__(self, blocks: List[Block]):
        self._blocks = blocks

    def plan_row_count(self) -> Optional[int]:
        return sum(b.num_rows for b in self._blocks)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for b in self._blocks:
            def read(b=b):
                yield b

            tasks.append(ReadTask(read, BlockMetadata(
                num_rows=b.num_rows, size_bytes=b.nbytes, schema=b.schema)))
        return tasks


def _expand_paths(paths, suffixes: Optional[List[str]] = None) -> List[str]:
    """Local dirs/globs plus any fsspec scheme (s3://, gs://,
    mock-remote://) — a TPU pod has no shared disk, so remote paths are
    the ONLY way pod workers can all reach the same training data
    (reference: file_based_datasource.py:65 resolves through pyarrow.fs).
    """
    from ray_tpu._private import fileio

    return fileio.expand_paths(paths, suffixes)


class FileBasedDatasource(Datasource):
    """One-or-more files per read task (reference:
    python/ray/data/datasource/file_based_datasource.py).  Paths may be
    local or any fsspec URI; read thunks re-resolve the filesystem on the
    worker from the path's scheme (nothing host-specific is pickled).
    """

    _suffixes: Optional[List[str]] = None

    def __init__(self, paths, **reader_args):
        self._paths = _expand_paths(paths, self._suffixes)
        self._reader_args = reader_args
        # per-path plan-metadata memo: footers are immutable per path,
        # and count() + execution would otherwise fetch each twice
        self._meta_memo: dict = {}

    def _read_file(self, path: str, **kwargs) -> Block:
        raise NotImplementedError

    def _plan_metadata(self, path: str):
        """Optional plan-time (num_rows, size_bytes, schema) for one file
        — parquet reads its footer; other formats return None and the
        plan falls back to byte-size estimates (reference:
        parquet_meta_provider.py vs DefaultFileMetadataProvider)."""
        return None

    def _plan_metadata_memo(self, path: str):
        if path not in self._meta_memo:
            try:
                result = self._plan_metadata(path)
            except Exception:
                # transient IO failure: DON'T cache — the next planning
                # call retries (None-by-design results do cache)
                return None
            self._meta_memo[path] = result
        return self._meta_memo[path]

    # footer reads at plan time are capped: past this many files the
    # per-file row counts are extrapolated from the sampled mean (the
    # reference's meta provider samples similarly for huge file lists)
    _PLAN_META_SAMPLE = 32

    def plan_row_count(self) -> Optional[int]:
        """Exact count from per-file plan metadata (parquet footers) —
        only when EVERY file is inside the sample cap and answers."""
        if len(self._paths) > self._PLAN_META_SAMPLE:
            return None
        total = 0
        for p in self._paths:
            m = self._plan_metadata_memo(p)
            if m is None:
                return None
            total += m[0]
        return total

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        from ray_tpu._private import fileio

        paths = self._paths
        parallelism = max(1, min(parallelism, len(paths)))
        groups: List[List[str]] = [[] for _ in range(parallelism)]
        for i, p in enumerate(paths):
            groups[i % parallelism].append(p)
        read_file = self._read_file
        args = self._reader_args

        meta_by_path = {}
        sample = paths[:self._PLAN_META_SAMPLE]
        for p in sample:
            meta_by_path[p] = self._plan_metadata_memo(p)
        sampled = [m for m in meta_by_path.values() if m is not None]
        mean_rows = (sum(m[0] for m in sampled) / len(sampled)
                     if sampled else None)
        mean_size = (sum(m[1] for m in sampled) / len(sampled)
                     if sampled else None)
        plan_schema = sampled[0][2] if sampled else None

        tasks = []
        for group in groups:
            if not group:
                continue

            def read(group=group):
                for p in group:
                    yield read_file(p, **args)

            rows = 0
            size = 0
            exact = bool(sampled)
            for p in group:
                m = meta_by_path.get(p)
                if m is not None:
                    rows += m[0]
                    size += m[1]
                elif mean_rows is not None:
                    # beyond the sample cap: extrapolate BOTH rows and
                    # bytes from the sampled means (no extra IO at plan
                    # time for 10k-file reads)
                    rows += int(mean_rows)
                    size += int(mean_size)
                    exact = False
                else:
                    exact = False
            if not sampled:
                size = sum(fileio.filesize(p) or 0 for p in group)
            tasks.append(ReadTask(read, BlockMetadata(
                num_rows=rows, size_bytes=size, schema=plan_schema,
                input_files=group, exec_stats={"rows_exact": exact})))
        return tasks


def _open(path: str, mode: str = "rb"):
    from ray_tpu._private import fileio

    return fileio.open_file(path, mode)


class ParquetDatasource(FileBasedDatasource):
    _suffixes = [".parquet"]

    def _read_file(self, path: str, columns=None, **kw) -> Block:
        import pyarrow.parquet as pq

        with _open(path) as f:
            return pq.read_table(f, columns=columns)

    def _plan_metadata(self, path: str):
        """Row count + schema from the parquet footer — a few KB read,
        no data pages touched (reference: parquet_meta_provider.py)."""
        import pyarrow.parquet as pq

        with _open(path) as f:
            pf = pq.ParquetFile(f)
            return (pf.metadata.num_rows,
                    pf.metadata.serialized_size
                    + sum(pf.metadata.row_group(i).total_byte_size
                          for i in range(pf.metadata.num_row_groups)),
                    pf.schema_arrow)


class CSVDatasource(FileBasedDatasource):
    _suffixes = [".csv"]

    def _read_file(self, path: str, **kw) -> Block:
        import pyarrow.csv as pcsv

        with _open(path) as f:
            return pcsv.read_csv(f)


class JSONDatasource(FileBasedDatasource):
    _suffixes = [".json", ".jsonl"]

    def _read_file(self, path: str, **kw) -> Block:
        import pyarrow.json as pjson

        with _open(path) as f:
            return pjson.read_json(f)


class TextDatasource(FileBasedDatasource):
    def _read_file(self, path: str, encoding="utf-8", drop_empty_lines=True,
                   **kw) -> Block:
        with _open(path) as f:
            # splitlines = universal newlines (\n, \r\n, \r) — the bytes
            # come straight off the remote fs with no text-mode layer
            lines = f.read().decode(encoding).splitlines()
        if drop_empty_lines:
            lines = [ln for ln in lines if ln.strip()]
        return pa.table({"text": lines})


class BinaryDatasource(FileBasedDatasource):
    def _read_file(self, path: str, include_paths=False, **kw) -> Block:
        with _open(path) as f:
            data = f.read()
        cols = {"bytes": [data]}
        if include_paths:
            cols["path"] = [path]
        return pa.table(cols)


class NumpyDatasource(FileBasedDatasource):
    _suffixes = [".npy"]

    def _read_file(self, path: str, **kw) -> Block:
        from .block import batch_to_block

        with _open(path) as f:
            return batch_to_block({"data": np.load(f)})


# ---------------------------------------------------------------------------
# Writers (run inside map tasks; reference: file_datasink.py)

def write_block(block: Block, path: str, file_format: str,
                **writer_args) -> str:
    """Persist one block under `path` (local dir or fsspec URI — pod
    workers write their shard straight to the remote fs; reference:
    file_datasink.py)."""
    from ray_tpu._private import fileio

    fileio.makedirs(path)
    sep = "/" if fileio.is_uri(path) else os.sep
    fname = f"{path.rstrip(sep)}{sep}{uuid.uuid4().hex[:12]}.{file_format}"
    if file_format == "parquet":
        import pyarrow.parquet as pq

        with fileio.open_file(fname, "wb") as f:
            pq.write_table(block, f, **writer_args)
    elif file_format == "csv":
        import pyarrow.csv as pcsv

        with fileio.open_file(fname, "wb") as f:
            pcsv.write_csv(block, f)
    elif file_format == "json":
        df = block.to_pandas()
        text = df.to_json(orient="records", lines=True)
        with fileio.open_file(fname, "wb") as f:
            f.write(text.encode())
    elif file_format == "npy":
        from .block import BlockAccessor

        cols = BlockAccessor(block).to_numpy()
        with fileio.open_file(fname, "wb") as f:
            if len(cols) == 1:
                np.save(f, next(iter(cols.values())))
            else:
                np.save(f, cols, allow_pickle=True)
    elif file_format == "tfrecords":
        from .block import BlockAccessor

        with fileio.open_file(fname, "wb") as f:
            for row in BlockAccessor(block).iter_rows():
                _tfrecord_write(f, _example_encode(row))
    elif file_format == "avro":
        from ._avro import write_container
        from .block import BlockAccessor

        rows = list(BlockAccessor(block).iter_rows())
        with fileio.open_file(fname, "wb") as f:
            f.write(write_container(rows, **writer_args))
    elif file_format == "tar":        # webdataset shard
        import io as _io
        import tarfile

        from .block import BlockAccessor

        encoder = writer_args.get("encoder")
        with fileio.open_file(fname, "wb") as f, \
                tarfile.open(fileobj=f, mode="w") as tf:
            for i, row in enumerate(BlockAccessor(block).iter_rows()):
                if callable(encoder):
                    row = encoder(row)
                key = str(row.get("__key__", f"{i:08d}"))
                for col, v in row.items():
                    if col in ("__key__", "__url__") or v is None:
                        continue
                    payload = (v if isinstance(v, bytes)
                               else _wds_encode_field(col, v))
                    info = tarfile.TarInfo(name=f"{key}.{col}")
                    info.size = len(payload)
                    tf.addfile(info, _io.BytesIO(payload))
    else:
        raise ValueError(f"unknown write format {file_format}")
    return fname


# ---------------------------------------------------------------------------
# TFRecord container format (pure python — the format is tiny: each record
# is len(u64 LE) + masked-crc32c(len) + payload + masked-crc32c(payload)).
# Reference: python/ray/data/datasource/tfrecords_datasource.py (which
# delegates to tf.io); payloads are tf.train.Example protos, which we
# encode/decode with a minimal hand-rolled proto codec (wire format only —
# Example = {1: Features{1: map<string, Feature>}}, Feature is a oneof of
# bytes_list(1)/float_list(2)/int64_list(3)).

_CRC_TABLE = None


def _crc32c(data: bytes) -> int:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


def _tfrecord_read(f) -> "Iterable[bytes]":
    import struct as _s

    while True:
        head = f.read(12)
        if not head:
            return
        if len(head) < 12:
            raise ValueError("truncated tfrecord file (partial header)")
        (length,), _ = _s.unpack("<Q", head[:8]), head[8:]
        payload = f.read(length)
        if len(payload) < length:
            raise ValueError(
                f"truncated tfrecord file (record claims {length} bytes, "
                f"got {len(payload)})")
        f.read(4)  # payload crc (not verified on read, like tf by default)
        yield payload


def _tfrecord_write(f, payload: bytes) -> None:
    import struct as _s

    head = _s.pack("<Q", len(payload))
    f.write(head)
    f.write(_s.pack("<I", _masked_crc(head)))
    f.write(payload)
    f.write(_s.pack("<I", _masked_crc(payload)))


# -- minimal protobuf wire helpers for tf.train.Example ---------------------

def _pb_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _pb_field(tag: int, payload: bytes) -> bytes:
    return _pb_varint((tag << 3) | 2) + _pb_varint(len(payload)) + payload


def _pb_read_varint(buf: bytes, i: int):
    n = shift = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _example_encode(row: "Dict[str, Any]") -> bytes:
    import struct as _s

    feats = b""
    for name, value in row.items():
        arr = np.asarray(value)
        if arr.dtype.kind in "SUO" or isinstance(value, (bytes, str)):
            vals = arr.reshape(-1).tolist() if arr.ndim else [arr.item()]
            for v in vals:
                if not isinstance(v, (bytes, str, np.bytes_, np.str_)):
                    # bytes(int) would write zero-filled garbage silently
                    raise ValueError(
                        f"tfrecords column {name!r}: unsupported value "
                        f"{type(v).__name__} (want int/float/bytes/str or "
                        "uniform lists thereof)")
            payload = b"".join(
                _pb_field(1, v.encode() if isinstance(v, str) else bytes(v))
                for v in vals)
            feature = _pb_field(1, payload)          # bytes_list = field 1
        elif arr.dtype.kind == "f":
            # float_list(field 2) { packed floats(field 1) }
            vals = arr.reshape(-1).astype("<f4")
            feature = _pb_field(2, _pb_field(1, vals.tobytes()))
        else:
            # int64_list(field 3) { packed varints(field 1) }
            ints = b"".join(_pb_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                            for v in arr.reshape(-1).tolist() or [])
            feature = _pb_field(3, _pb_field(1, ints))
        entry = _pb_field(1, name.encode()) + _pb_field(2, feature)
        feats += _pb_field(1, entry)                 # map entry
    return _pb_field(1, feats)                        # Example.features


def _example_decode(payload: bytes) -> "Dict[str, Any]":
    def read_fields(buf):
        i = 0
        while i < len(buf):
            key, i = _pb_read_varint(buf, i)
            tag, wire = key >> 3, key & 7
            if wire == 2:
                ln, i = _pb_read_varint(buf, i)
                yield tag, buf[i:i + ln]
                i += ln
            elif wire == 0:
                v, i = _pb_read_varint(buf, i)
                yield tag, v
            elif wire == 5:
                yield tag, buf[i:i + 4]
                i += 4
            elif wire == 1:
                yield tag, buf[i:i + 8]
                i += 8
            else:
                raise ValueError(f"bad wire type {wire}")

    row: Dict[str, Any] = {}
    for tag, features in read_fields(payload):
        if tag != 1:
            continue
        for etag, entry in read_fields(features):
            if etag != 1:
                continue
            name, feature = None, None
            for ftag, fval in read_fields(entry):
                if ftag == 1:
                    name = fval.decode()
                elif ftag == 2:
                    feature = fval
            if name is None or feature is None:
                continue
            for kind, lst in read_fields(feature):
                vals: List[Any] = []
                if kind == 1:      # bytes_list
                    vals = [v for t, v in read_fields(lst) if t == 1]
                elif kind == 2:    # float_list: packed bytes OR repeated
                    for t, v in read_fields(lst):   # fixed32 (unpacked)
                        if t != 1:
                            continue
                        if isinstance(v, (bytes, bytearray)):
                            vals.extend(np.frombuffer(v, "<f4").tolist())
                        else:
                            vals.append(v)
                elif kind == 3:    # int64_list: packed varints OR unpacked
                    for t, v in read_fields(lst):
                        if t != 1:
                            continue
                        if isinstance(v, (bytes, bytearray)):
                            i = 0
                            while i < len(v):
                                n, i = _pb_read_varint(v, i)
                                vals.append(n)
                        else:
                            vals.append(v)
                    vals = [n - (1 << 64) if n >= 1 << 63 else n
                            for n in vals]
                row[name] = vals[0] if len(vals) == 1 else vals
    return row


class TFRecordsDatasource(FileBasedDatasource):
    _suffixes = [".tfrecords", ".tfrecord"]

    def _read_file(self, path: str, **kw) -> Block:
        rows = []
        with _open(path) as f:
            for payload in _tfrecord_read(f):
                rows.append(_example_decode(payload))
        return rows_to_block(rows)


class AvroDatasource(FileBasedDatasource):
    """reference: read_api.py read_avro (delegates to fastavro there;
    here the container format + binary encoding are implemented directly
    — see _avro.py — so the connector needs no third-party library)."""

    _suffixes = [".avro"]

    def _read_file(self, path: str, **kw) -> Block:
        from ._avro import read_container

        with _open(path) as f:
            return rows_to_block(read_container(f.read()))


_WDS_IMAGE_EXTS = ("jpg", "jpeg", "png", "bmp", "gif", "ppm")


def _wds_decode_field(ext: str, data: bytes, decoder):
    """Default per-field decoder (reference:
    _internal/datasource/webdataset_datasource.py default_decoder):
    extension picks the codec; unknown extensions stay raw bytes."""
    if decoder is False or decoder is None:
        return data
    base = ext.rsplit(".", 1)[-1].lower()
    if base in ("txt", "text", "transcript"):
        return data.decode("utf-8")
    if base in ("cls", "cls2", "index", "inx", "id"):
        return int(data.decode("utf-8").strip())
    if base in ("json", "jsn"):
        import json as _json

        return _json.loads(data.decode("utf-8"))
    if base in ("npy", "npz"):
        import io as _io

        return np.load(_io.BytesIO(data), allow_pickle=False)
    if base in _WDS_IMAGE_EXTS:
        try:
            import io as _io

            from PIL import Image
        except ImportError:
            return data            # no PIL: hand back the encoded bytes
        img = Image.open(_io.BytesIO(data))
        img.load()
        return np.asarray(img)
    return data


class WebDatasetDatasource(FileBasedDatasource):
    """WebDataset tar shards (reference: read_api.py:1840 read_webdataset,
    _internal/datasource/webdataset_datasource.py — which wraps the
    webdataset library's tar iterator; here the format is read directly:
    a sample is the run of consecutive tar members sharing a basename up
    to its first dot, fields keyed by the remaining extension)."""

    _suffixes = [".tar"]

    def _read_file(self, path: str, decoder=True, fileselect=None,
                   filerename=None, suffixes=None, include_paths=False,
                   **kw) -> Block:
        import tarfile

        def renamed(ext: str) -> str:
            if callable(filerename):
                return filerename(ext)
            for old, new in filerename or []:
                if ext == old:
                    return new
            return ext

        def keep(ext: str) -> bool:
            for flt in (fileselect, suffixes):
                if flt is None:
                    continue
                if callable(flt) and not flt(ext):
                    return False
                if isinstance(flt, (list, tuple, set)):
                    # suffix-match like the reference: "png" keeps both
                    # "png" and compound extensions like "seg.png"
                    if not any(ext == s or ext.endswith("." + s)
                               for s in flt):
                        return False
            return True

        rows: List[dict] = []

        def flush(key, fields):
            if key is None or not fields:
                return
            row = {"__key__": key}
            if include_paths:
                row["__url__"] = path
            row.update(fields)
            rows.append(row)

        # custom decoders (single callable or a chain) see the RAW bytes
        # sample — default per-extension decoding applies only when
        # decoder is True
        custom = callable(decoder) or isinstance(decoder, (list, tuple))

        with _open(path) as f, tarfile.open(fileobj=f, mode="r|*") as tf:
            cur_key, cur = None, {}
            for member in tf:
                if not member.isfile():
                    continue
                name = member.name
                name = name[2:] if name.startswith("./") else name
                dirpart, _, base = name.rpartition("/")
                if base.startswith("."):
                    continue
                # the key keeps the directory prefix (reference
                # base_plus_ext: two subdirs may reuse a basename and
                # must stay distinct samples); ext splits at the FIRST
                # dot of the basename only
                stem, _, ext = base.partition(".")
                key = f"{dirpart}/{stem}" if dirpart else stem
                # the key change must be observed BEFORE any field
                # filtering: a filtered-out member still delimits samples
                # (else two same-key runs separated only by filtered
                # members would silently merge)
                if key != cur_key:
                    flush(cur_key, cur)
                    cur_key, cur = key, {}
                ext = renamed(ext)
                if not ext or not keep(ext):
                    continue
                data = tf.extractfile(member).read()
                cur[ext] = (data if custom
                            else _wds_decode_field(ext, data, decoder))
            flush(cur_key, cur)
        if callable(decoder):
            rows = [decoder(r) for r in rows]
        elif isinstance(decoder, (list, tuple)):
            for fn in decoder:
                rows = [fn(r) for r in rows]
        return rows_to_block(rows)


def _wds_encode_field(ext: str, value) -> bytes:
    """Default per-field encoder for write_webdataset (reference:
    _internal/datasource/webdataset_datasink.py default_encoder)."""
    if isinstance(value, (bytes, bytearray, memoryview)):
        return bytes(value)
    base = ext.rsplit(".", 1)[-1].lower()
    if isinstance(value, np.generic) and not isinstance(value, np.ndarray):
        # arrow blocks yield numpy scalars (np.float32/np.bool_/...),
        # which neither the int branch nor json.dumps accepts
        value = value.item()
    if isinstance(value, str):
        return value.encode("utf-8")
    if isinstance(value, (bool, int)):
        return str(int(value)).encode("utf-8")
    if isinstance(value, np.ndarray) and base in _WDS_IMAGE_EXTS:
        import io as _io

        from PIL import Image

        fmt = {"png": "PNG", "jpg": "JPEG", "jpeg": "JPEG", "bmp": "BMP",
               "ppm": "PPM", "gif": "GIF"}[base]
        buf = _io.BytesIO()
        Image.fromarray(value).save(buf, format=fmt)
        return buf.getvalue()
    if isinstance(value, np.ndarray):
        import io as _io

        buf = _io.BytesIO()
        np.save(buf, value, allow_pickle=False)
        return buf.getvalue()
    import json as _json

    return _json.dumps(value).encode("utf-8")


class ImagesDatasource(FileBasedDatasource):
    """reference: python/ray/data/datasource/image_datasource.py"""

    _suffixes = [".png", ".jpg", ".jpeg", ".bmp", ".gif"]

    def _read_file(self, path: str, size=None, mode=None,
                   include_paths=False, **kw) -> Block:
        from PIL import Image

        from .block import batch_to_block

        with _open(path) as f:
            img = Image.open(f)
            img.load()
        if mode:
            img = img.convert(mode)
        if size:
            img = img.resize((size[1], size[0]))
        arr = np.asarray(img)
        batch = {"image": arr[None]}
        if include_paths:
            batch["path"] = np.array([path])
        return batch_to_block(batch)


class SQLDatasource(Datasource):
    """reference: python/ray/data/datasource/sql_datasource.py — any DB-API
    connection factory (sqlite3, psycopg2, ...)."""

    def __init__(self, sql: str, connection_factory):
        self._sql = sql
        self._factory = connection_factory

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        sql, factory = self._sql, self._factory

        def read():
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(sql)
                cols = [d[0] for d in cur.description]
                rows = [dict(zip(cols, r)) for r in cur.fetchall()]
            finally:
                conn.close()
            yield rows_to_block(rows)

        return [ReadTask(read, BlockMetadata(num_rows=0, size_bytes=0))]
