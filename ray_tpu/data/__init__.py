"""ray_tpu.data: distributed datasets over the ray_tpu task runtime.

Capability-parity redesign of the reference's Ray Data (reference:
python/ray/data/ — Dataset, read_api.py, streaming executor): lazy logical
plans over arrow blocks, a pull-based streaming executor running map
transforms as ray_tpu tasks with bounded in-flight budgets, all-to-all
exchanges (shuffle/sort/groupby), and device-fed iteration
(`iter_jax_batches`) that double-buffers batches into TPU HBM.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

from . import aggregate
from .aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from .block import Block, BlockAccessor, BlockMetadata
from .compute import ActorPoolStrategy, TaskPoolStrategy
from .context import DataContext
from .dataset import Dataset, MaterializedDataset
from .datasource import (BinaryDatasource, BlocksDatasource, CSVDatasource,
                         Datasource, ItemsDatasource, JSONDatasource,
                         NumpyDatasource, ParquetDatasource, RangeDatasource,
                         ReadTask, TextDatasource)
from .grouped import GroupedData
from .logical import LogicalPlan, Read
from .preprocessors import (BatchMapper, Chain, Concatenator, LabelEncoder,
                            MaxAbsScaler, MinMaxScaler, OneHotEncoder,
                            OrdinalEncoder, Preprocessor, SimpleImputer,
                            StandardScaler)


def read_datasource(datasource: Datasource, *,
                    override_num_blocks: Optional[int] = None) -> Dataset:
    """reference: python/ray/data/read_api.py:334"""
    return Dataset(Read(datasource, override_num_blocks or -1))


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    return read_datasource(RangeDatasource(n),
                           override_num_blocks=override_num_blocks
                           or min(n, 16) or 1)


def range_tensor(n: int, *, shape=(1,),
                 override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(RangeDatasource(n, tensor_shape=tuple(shape)),
                           override_num_blocks=override_num_blocks
                           or min(n, 16) or 1)


def from_items(items: List[Any], *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(ItemsDatasource(items),
                           override_num_blocks=override_num_blocks
                           or min(len(items), 8) or 1)


def from_numpy(arr: np.ndarray, column: str = "data") -> Dataset:
    from .block import batch_to_block

    return read_datasource(
        BlocksDatasource([batch_to_block({column: np.asarray(arr)})]))


def from_pandas(dfs) -> Dataset:
    import pandas as pd
    import pyarrow as pa

    if isinstance(dfs, pd.DataFrame):
        dfs = [dfs]
    blocks = [pa.Table.from_pandas(df, preserve_index=False) for df in dfs]
    return read_datasource(BlocksDatasource(blocks))


def from_arrow(tables) -> Dataset:
    import pyarrow as pa

    if isinstance(tables, pa.Table):
        tables = [tables]
    return read_datasource(BlocksDatasource(list(tables)))


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(ParquetDatasource(paths, columns=columns),
                           override_num_blocks=override_num_blocks)


def read_csv(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(CSVDatasource(paths),
                           override_num_blocks=override_num_blocks)


def read_json(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(JSONDatasource(paths),
                           override_num_blocks=override_num_blocks)


def read_text(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(TextDatasource(paths),
                           override_num_blocks=override_num_blocks)


def read_binary_files(paths, *, include_paths: bool = False,
                      override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(
        BinaryDatasource(paths, include_paths=include_paths),
        override_num_blocks=override_num_blocks)


def read_numpy(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(NumpyDatasource(paths),
                           override_num_blocks=override_num_blocks)


def read_tfrecords(paths, *,
                   override_num_blocks: Optional[int] = None) -> Dataset:
    """reference: python/ray/data/read_api.py read_tfrecords (tf.train.Example
    records; decoded with a dependency-free proto/container codec)."""
    from .datasource import TFRecordsDatasource

    return read_datasource(TFRecordsDatasource(paths),
                           override_num_blocks=override_num_blocks)


def read_images(paths, *, size=None, mode=None, include_paths: bool = False,
                override_num_blocks: Optional[int] = None) -> Dataset:
    """reference: python/ray/data/read_api.py read_images (PIL-decoded)."""
    from .datasource import ImagesDatasource

    return read_datasource(
        ImagesDatasource(paths, size=size, mode=mode,
                         include_paths=include_paths),
        override_num_blocks=override_num_blocks)


def read_avro(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    """reference: read_api.py read_avro — Object Container Files, read
    with a dependency-free spec-level codec (datasource.AvroDatasource;
    null + deflate codecs, nullable unions)."""
    from .datasource import AvroDatasource

    return read_datasource(AvroDatasource(paths),
                           override_num_blocks=override_num_blocks)


def read_webdataset(paths, *, decoder=True, fileselect=None, filerename=None,
                    suffixes=None, include_paths: bool = False,
                    override_num_blocks: Optional[int] = None) -> Dataset:
    """reference: read_api.py:1840 read_webdataset — tar shards of
    key-grouped samples, read with a tar-native dependency-free codec
    (datasource.WebDatasetDatasource).  `decoder` True applies per-
    extension defaults (txt/cls/json/npy/images), False keeps raw bytes,
    a callable (or list of callables) maps each sample dict."""
    from .datasource import WebDatasetDatasource

    return read_datasource(
        WebDatasetDatasource(paths, decoder=decoder, fileselect=fileselect,
                             filerename=filerename, suffixes=suffixes,
                             include_paths=include_paths),
        override_num_blocks=override_num_blocks)


def read_sql(sql: str, connection_factory, *,
             override_num_blocks: Optional[int] = None) -> Dataset:
    """reference: python/ray/data/read_api.py read_sql — any DB-API
    connection factory (sqlite3.connect closure, psycopg2, ...)."""
    from .datasource import SQLDatasource

    return read_datasource(SQLDatasource(sql, connection_factory),
                           override_num_blocks=override_num_blocks)


def read_delta(table_uri: str, *, version: Optional[int] = None,
               columns: Optional[List[str]] = None,
               override_num_blocks: Optional[int] = None) -> Dataset:
    """Read a Delta Lake table snapshot (with `version=` time travel).

    reference: read_api.py read_delta_sharing_tables — here the open
    table protocol (_delta_log replay + checkpoints) is read directly,
    local or remote (lake.DeltaDatasource)."""
    from .lake import DeltaDatasource

    return read_datasource(
        DeltaDatasource(table_uri, version=version, columns=columns),
        override_num_blocks=override_num_blocks)


def read_iceberg(table_uri: str, *, snapshot_id: Optional[int] = None,
                 columns: Optional[List[str]] = None,
                 override_num_blocks: Optional[int] = None) -> Dataset:
    """Read an Apache Iceberg v1/v2 table snapshot.

    reference: read_api.py read_iceberg (pyiceberg) — here the
    metadata.json -> manifest-list -> manifest avro chain is walked with
    the bundled codec (lake.IcebergDatasource)."""
    from .lake import IcebergDatasource

    return read_datasource(
        IcebergDatasource(table_uri, snapshot_id=snapshot_id,
                          columns=columns),
        override_num_blocks=override_num_blocks)


def read_parquet_bulk(paths, *, columns: Optional[List[str]] = None,
                      override_num_blocks: Optional[int] = None) -> Dataset:
    """reference: read_parquet_bulk — one file per read unit, skipping
    metadata consolidation (for many small files)."""
    return read_datasource(ParquetDatasource(paths, columns=columns),
                           override_num_blocks=override_num_blocks
                           or 200)


def from_blocks(blocks: List[Block]) -> Dataset:
    return read_datasource(BlocksDatasource(list(blocks)),
                           override_num_blocks=len(blocks) or 1)


def _from_refs(refs, to_block) -> Dataset:
    """Dataset over already-stored objects: each read task resolves its
    ref on a worker (the owner keeps them pinned via the closure)."""
    from .datasource import BlockMetadata, Datasource, ReadTask

    class _RefsDatasource(Datasource):
        def get_read_tasks(self, parallelism):
            tasks = []
            for r in refs:
                def read(r=r):
                    yield to_block(ray_tpu.get(r, timeout=600))

                tasks.append(ReadTask(read, BlockMetadata(num_rows=0,
                                                          size_bytes=0)))
            return tasks

    return read_datasource(_RefsDatasource(),
                           override_num_blocks=len(refs) or 1)


def from_arrow_refs(refs) -> Dataset:
    return _from_refs(list(refs), lambda t: t)


def _df_to_table(df):
    import pyarrow as pa

    return pa.Table.from_pandas(df)


def from_pandas_refs(refs) -> Dataset:
    return _from_refs(list(refs), _df_to_table)


def from_numpy_refs(refs, column: str = "data") -> Dataset:
    from .block import batch_to_block

    return _from_refs(list(refs), lambda a: batch_to_block({column: a}))


def from_huggingface(hf_dataset) -> Dataset:
    """reference: read_api.py from_huggingface (datasets.Dataset holds an
    arrow table; split it into row-group blocks)."""
    table = hf_dataset.data.table if hasattr(hf_dataset, "data") else None
    if table is None:
        import pyarrow as pa

        table = pa.Table.from_pydict(hf_dataset.to_dict())
    return from_arrow(table.combine_chunks())


def from_torch(torch_dataset) -> Dataset:
    """reference: read_api.py from_torch (map-style torch dataset).
    Lazy: each read task materializes its own index range on a worker —
    the dataset object (not its contents) travels in the task closure."""
    import builtins

    from .block import rows_to_block
    from .datasource import BlockMetadata, Datasource, ReadTask

    n = len(torch_dataset)

    class _TorchDatasource(Datasource):
        def get_read_tasks(self, parallelism):
            parallelism = max(1, min(parallelism, n or 1))
            chunk = (n + parallelism - 1) // parallelism if n else 0
            tasks = []
            for start in builtins.range(0, n, max(chunk, 1)):
                end = min(start + chunk, n)

                def read(start=start, end=end):
                    yield rows_to_block(
                        [{"item": torch_dataset[i]}
                         for i in builtins.range(start, end)])

                tasks.append(ReadTask(read, BlockMetadata(
                    num_rows=end - start, size_bytes=0)))
            return tasks

    return read_datasource(_TorchDatasource(),
                           override_num_blocks=min(n, 8) or 1)


def from_tf(tf_dataset) -> Dataset:
    """reference: read_api.py from_tf (finite tf.data.Dataset)."""
    rows = []
    for el in tf_dataset.as_numpy_iterator():
        if isinstance(el, dict):
            rows.append(el)
        elif isinstance(el, tuple):
            rows.append({f"f{i}": v for i, v in enumerate(el)})
        else:
            rows.append({"item": el})
    return from_items(rows)


def _unavailable(name: str, dep: str):
    def fn(*a, **kw):
        raise ImportError(
            f"ray_tpu.data.{name} requires {dep}, which is not available "
            "in this environment (external-service connectors are gated)")
    fn.__name__ = name
    return fn


def read_bigquery(project_id: str, dataset: Optional[str] = None,
                  query: Optional[str] = None, *,
                  client_factory=None,
                  override_num_blocks: Optional[int] = None) -> Dataset:
    """reference: python/ray/data/read_api.py read_bigquery (:523).

    Table reads fan out over storage-API read streams; query reads run
    server-side.  `client_factory` injects a duck-typed client (tests /
    alternative transports); omitted, the google client library is
    imported lazily and its absence raises ImportError."""
    from .external import BigQueryDatasource

    return read_datasource(
        BigQueryDatasource(project_id, dataset, query,
                           client_factory=client_factory),
        override_num_blocks=override_num_blocks)


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: Optional[List[dict]] = None,
               client_factory=None,
               override_num_blocks: Optional[int] = None) -> Dataset:
    """reference: python/ray/data/read_api.py read_mongo (:423).

    Partitioned server-side aggregation reads.  `client_factory(uri)`
    injects a pymongo-shaped client; omitted, pymongo is imported
    lazily and its absence raises ImportError."""
    from .external import MongoDatasource

    return read_datasource(
        MongoDatasource(uri, database, collection, pipeline,
                        client_factory=client_factory),
        override_num_blocks=override_num_blocks)


# external-service connectors: present for API parity, gated on their
# client libraries exactly like the reference gates them
read_databricks_tables = _unavailable("read_databricks_tables",
                                      "databricks-sql-connector")
read_delta_sharing_tables = _unavailable("read_delta_sharing_tables",
                                         "delta-sharing")
read_lance = _unavailable("read_lance", "lance")
from_spark = _unavailable("from_spark", "pyspark")
from_dask = _unavailable("from_dask", "dask")
from_mars = _unavailable("from_mars", "mars")
from_modin = _unavailable("from_modin", "modin")


__all__ = [
    "Dataset", "MaterializedDataset", "DataContext", "GroupedData",
    "Datasource", "ReadTask", "Block", "BlockAccessor", "BlockMetadata",
    "AggregateFn", "Count", "Sum", "Min", "Max", "Mean", "Std",
    "read_datasource", "range", "range_tensor", "from_items", "from_numpy",
    "from_pandas", "from_arrow", "read_parquet", "read_csv", "read_json",
    "read_text", "read_binary_files", "read_numpy", "aggregate",
    "read_avro", "read_tfrecords", "read_images", "read_sql",
    "read_webdataset", "read_bigquery", "read_mongo",
    "read_parquet_bulk", "read_delta", "read_iceberg",
    "from_blocks", "from_arrow_refs", "from_pandas_refs", "from_numpy_refs",
    "from_huggingface", "from_torch", "from_tf",
    "ActorPoolStrategy", "TaskPoolStrategy",
]
