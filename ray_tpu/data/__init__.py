"""ray_tpu.data: distributed datasets over the ray_tpu task runtime.

Capability-parity redesign of the reference's Ray Data (reference:
python/ray/data/ — Dataset, read_api.py, streaming executor): lazy logical
plans over arrow blocks, a pull-based streaming executor running map
transforms as ray_tpu tasks with bounded in-flight budgets, all-to-all
exchanges (shuffle/sort/groupby), and device-fed iteration
(`iter_jax_batches`) that double-buffers batches into TPU HBM.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

from . import aggregate
from .aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from .block import Block, BlockAccessor, BlockMetadata
from .context import DataContext
from .dataset import Dataset, MaterializedDataset
from .datasource import (BinaryDatasource, BlocksDatasource, CSVDatasource,
                         Datasource, ItemsDatasource, JSONDatasource,
                         NumpyDatasource, ParquetDatasource, RangeDatasource,
                         ReadTask, TextDatasource)
from .grouped import GroupedData
from .logical import LogicalPlan, Read
from .preprocessors import (BatchMapper, Chain, Concatenator, LabelEncoder,
                            MaxAbsScaler, MinMaxScaler, OneHotEncoder,
                            OrdinalEncoder, Preprocessor, SimpleImputer,
                            StandardScaler)


def read_datasource(datasource: Datasource, *,
                    override_num_blocks: Optional[int] = None) -> Dataset:
    """reference: python/ray/data/read_api.py:334"""
    return Dataset(Read(datasource, override_num_blocks or -1))


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    return read_datasource(RangeDatasource(n),
                           override_num_blocks=override_num_blocks
                           or min(n, 16) or 1)


def range_tensor(n: int, *, shape=(1,),
                 override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(RangeDatasource(n, tensor_shape=tuple(shape)),
                           override_num_blocks=override_num_blocks
                           or min(n, 16) or 1)


def from_items(items: List[Any], *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(ItemsDatasource(items),
                           override_num_blocks=override_num_blocks
                           or min(len(items), 8) or 1)


def from_numpy(arr: np.ndarray, column: str = "data") -> Dataset:
    from .block import batch_to_block

    return read_datasource(
        BlocksDatasource([batch_to_block({column: np.asarray(arr)})]))


def from_pandas(dfs) -> Dataset:
    import pandas as pd
    import pyarrow as pa

    if isinstance(dfs, pd.DataFrame):
        dfs = [dfs]
    blocks = [pa.Table.from_pandas(df, preserve_index=False) for df in dfs]
    return read_datasource(BlocksDatasource(blocks))


def from_arrow(tables) -> Dataset:
    import pyarrow as pa

    if isinstance(tables, pa.Table):
        tables = [tables]
    return read_datasource(BlocksDatasource(list(tables)))


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(ParquetDatasource(paths, columns=columns),
                           override_num_blocks=override_num_blocks)


def read_csv(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(CSVDatasource(paths),
                           override_num_blocks=override_num_blocks)


def read_json(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(JSONDatasource(paths),
                           override_num_blocks=override_num_blocks)


def read_text(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(TextDatasource(paths),
                           override_num_blocks=override_num_blocks)


def read_binary_files(paths, *, include_paths: bool = False,
                      override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(
        BinaryDatasource(paths, include_paths=include_paths),
        override_num_blocks=override_num_blocks)


def read_numpy(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return read_datasource(NumpyDatasource(paths),
                           override_num_blocks=override_num_blocks)


__all__ = [
    "Dataset", "MaterializedDataset", "DataContext", "GroupedData",
    "Datasource", "ReadTask", "Block", "BlockAccessor", "BlockMetadata",
    "AggregateFn", "Count", "Sum", "Min", "Max", "Mean", "Std",
    "read_datasource", "range", "range_tensor", "from_items", "from_numpy",
    "from_pandas", "from_arrow", "read_parquet", "read_csv", "read_json",
    "read_text", "read_binary_files", "read_numpy", "aggregate",
]
