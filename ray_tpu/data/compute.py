"""Compute strategies for map operations.

Reference: python/ray/data/_internal/compute.py (TaskPoolStrategy,
ActorPoolStrategy) — the knob deciding whether a `map_batches` fans out
as stateless tasks or runs on a pool of long-lived actors holding warm
per-actor state (the TPU batch-inference pattern: load a model / compile
a program once per actor, reuse it for every block).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple, Union


@dataclass
class TaskPoolStrategy:
    """Stateless tasks; `size` caps this operator's concurrent tasks."""

    size: Optional[int] = None


@dataclass
class ActorPoolStrategy:
    """Autoscaling pool of worker actors (reference: compute.py
    ActorPoolStrategy).  min_size actors start up front; the pool grows
    toward max_size while inputs queue faster than the pool drains, and
    dead actors are replaced with their in-flight blocks resubmitted."""

    min_size: int = 1
    max_size: Optional[int] = None
    max_tasks_in_flight_per_actor: int = 2

    def __post_init__(self):
        if self.max_size is None:
            self.max_size = self.min_size
        if self.min_size < 1 or self.max_size < self.min_size:
            raise ValueError(
                f"invalid actor pool bounds ({self.min_size}, "
                f"{self.max_size})")


def strategy_from_concurrency(
        concurrency: Union[int, Tuple[int, int], None],
        is_class_udf: bool):
    """Map the user-facing `concurrency` argument onto a strategy
    (reference: dataset.py map_batches `concurrency` semantics)."""
    if concurrency is None:
        if is_class_udf:
            raise ValueError(
                "a callable-class UDF requires `concurrency` (int for a "
                "fixed-size actor pool, (min, max) for autoscaling)")
        return TaskPoolStrategy()
    if isinstance(concurrency, int):
        if concurrency < 1:
            raise ValueError(f"concurrency must be >= 1, got {concurrency}")
        if is_class_udf:
            return ActorPoolStrategy(concurrency, concurrency)
        return TaskPoolStrategy(size=concurrency)
    if (isinstance(concurrency, tuple) and len(concurrency) == 2
            and all(isinstance(x, int) for x in concurrency)):
        if not is_class_udf:
            raise ValueError(
                "(min, max) concurrency is only valid for callable-class "
                "UDFs; pass an int to cap task concurrency")
        return ActorPoolStrategy(concurrency[0], concurrency[1])
    raise ValueError(
        f"concurrency must be an int or (min, max) tuple, got "
        f"{concurrency!r}")
