"""Batch iteration, including device-fed iteration for TPU training.

Reference: python/ray/data/iterator.py (iter_batches, iter_torch_batches).
TPU-native twist: ``iter_jax_batches`` stages host batches into HBM with
double buffering — ``jax.device_put`` of batch N+1 is issued while batch N
is being consumed, so input feeding overlaps the device step (the role the
reference delegates to torch DataLoader pinned-memory prefetch).
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu

from .block import BlockAccessor
from .context import DataContext


def iter_block_batches(block_iter, *, batch_size: Optional[int],
                       batch_format: str, drop_last: bool = False,
                       local_shuffle_buffer_size: Optional[int] = None,
                       seed: Optional[int] = None):
    """Re-batch a stream of blocks into fixed-size batches."""
    carry = None  # carry-over arrow table smaller than batch_size
    rng = np.random.RandomState(seed)
    shuffle_pool: List[Any] = []

    def emit(table):
        return BlockAccessor(table).to_batch(batch_format)

    for block in block_iter:
        acc = BlockAccessor(block)
        if acc.num_rows() == 0:
            continue
        table = acc.to_arrow()
        if local_shuffle_buffer_size:
            table = BlockAccessor(table).random_permutation(
                int(rng.randint(0, 2**31)))
        if carry is not None:
            table = BlockAccessor.concat([carry, table])
            carry = None
        if batch_size is None:
            yield emit(table)
            continue
        n = table.num_rows
        start = 0
        while n - start >= batch_size:
            yield emit(table.slice(start, batch_size))
            start += batch_size
        if start < n:
            carry = table.slice(start)
    if carry is not None and not drop_last:
        yield emit(carry)


def prefetch_iter(it: Iterator, depth: int) -> Iterator:
    """Run `it` in a background thread with a bounded queue."""
    if depth <= 0:
        yield from it
        return
    q: queue.Queue = queue.Queue(maxsize=depth)
    DONE = object()
    err: List[BaseException] = []

    def worker():
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # propagate to consumer
            err.append(e)
        finally:
            q.put(DONE)

    t = threading.Thread(target=worker, daemon=True, name="data-prefetch")
    t.start()
    while True:
        item = q.get()
        if item is DONE:
            if err:
                raise err[0]
            return
        yield item


def iter_jax_batches(batch_iter: Iterator[Dict[str, np.ndarray]], *,
                     sharding=None, dtypes: Optional[Dict[str, Any]] = None,
                     prefetch: int = 2) -> Iterator:
    """Move numpy batches onto device with double buffering.

    With a `jax.sharding.Sharding` (e.g. NamedSharding over a data axis),
    each batch is placed sharded across the mesh; otherwise it goes to the
    default device.
    """
    import jax

    def put(batch):
        def place(x):
            arr = np.asarray(x)
            if dtypes and getattr(x, "dtype", None) is not None:
                pass
            if sharding is not None:
                return jax.device_put(arr, sharding)
            return jax.device_put(arr)

        if isinstance(batch, dict):
            out = {k: place(v) for k, v in batch.items()}
        else:
            out = place(batch)
        return out

    buf: collections.deque = collections.deque()
    it = iter(batch_iter)
    # fill the pipeline
    try:
        for _ in range(max(1, prefetch)):
            buf.append(put(next(it)))
    except StopIteration:
        pass
    for batch in it:
        nxt = put(batch)  # enqueue transfer for N+1 before yielding N
        yield buf.popleft()
        buf.append(nxt)
    while buf:
        yield buf.popleft()
