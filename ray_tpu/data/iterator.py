"""Batch iteration, including device-fed iteration for TPU training.

Reference: python/ray/data/iterator.py (iter_batches, iter_torch_batches).
TPU-native twist: ``iter_jax_batches`` stages host batches into HBM with
double buffering — ``jax.device_put`` of batch N+1 is issued while batch N
is being consumed, so input feeding overlaps the device step (the role the
reference delegates to torch DataLoader pinned-memory prefetch).
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu

from .block import BlockAccessor
from .context import DataContext


def iter_block_batches(block_iter, *, batch_size: Optional[int],
                       batch_format: str, drop_last: bool = False,
                       local_shuffle_buffer_size: Optional[int] = None,
                       seed: Optional[int] = None):
    """Re-batch a stream of blocks into fixed-size batches."""
    if local_shuffle_buffer_size:
        yield from _iter_shuffled_batches(
            block_iter, batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last, buffer_size=local_shuffle_buffer_size,
            seed=seed)
        return
    carry = None  # carry-over arrow table smaller than batch_size

    def emit(table):
        return BlockAccessor(table).to_batch(batch_format)

    for block in block_iter:
        acc = BlockAccessor(block)
        if acc.num_rows() == 0:
            continue
        table = acc.to_arrow()
        if carry is not None:
            table = BlockAccessor.concat([carry, table])
            carry = None
        if batch_size is None:
            yield emit(table)
            continue
        n = table.num_rows
        start = 0
        while n - start >= batch_size:
            yield emit(table.slice(start, batch_size))
            start += batch_size
        if start < n:
            carry = table.slice(start)
    if carry is not None and not drop_last:
        yield emit(carry)


def _iter_shuffled_batches(block_iter, *, batch_size, batch_format,
                           drop_last, buffer_size, seed):
    """Local shuffle: rows pool in a buffer that mixes ACROSS blocks; once
    the pool holds >= buffer_size + batch_size rows it is permuted and
    batches are drawn from it (reference: iterator's
    local_shuffle_buffer_size contract — a bigger buffer means more
    mixing)."""
    rng = np.random.RandomState(seed)
    bs = batch_size or int(buffer_size)
    buf = None

    def emit(table):
        return BlockAccessor(table).to_batch(batch_format)

    def permute(table):
        return BlockAccessor(table).random_permutation(
            int(rng.randint(0, 2**31)))

    for block in block_iter:
        acc = BlockAccessor(block)
        if acc.num_rows() == 0:
            continue
        t = acc.to_arrow()
        buf = t if buf is None else BlockAccessor.concat([buf, t])
        if buf.num_rows >= buffer_size + bs:
            buf = permute(buf)
            while buf.num_rows >= buffer_size + bs:
                yield emit(buf.slice(0, bs))
                buf = buf.slice(bs)
    if buf is not None and buf.num_rows:
        buf = permute(buf)
        start = 0
        while buf.num_rows - start >= bs:
            yield emit(buf.slice(start, bs))
            start += bs
        if start < buf.num_rows and not drop_last:
            yield emit(buf.slice(start))


def prefetch_iter(it: Iterator, depth: int) -> Iterator:
    """Run `it` in a background thread with a bounded queue."""
    if depth <= 0:
        yield from it
        return
    q: queue.Queue = queue.Queue(maxsize=depth)
    DONE = object()
    err: List[BaseException] = []

    # propagate task context: the spawning thread may be executing a task
    # (e.g. a Train worker's loop); the prefetcher does that task's
    # blocking get()s, so it must count as the task for the raylet's
    # blocked-CPU lending or a fully-reserved node deadlocks
    from ray_tpu._private.core import adopt_task_context

    def worker():
        adopt_task_context()
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # propagate to consumer
            err.append(e)
        finally:
            q.put(DONE)

    t = threading.Thread(target=worker, daemon=True, name="data-prefetch")
    t.start()
    while True:
        item = q.get()
        if item is DONE:
            if err:
                raise err[0]
            return
        yield item


def iter_jax_batches(batch_iter: Iterator[Dict[str, np.ndarray]], *,
                     sharding=None, dtypes: Optional[Dict[str, Any]] = None,
                     prefetch: int = 2) -> Iterator:
    """Move numpy batches onto device with double buffering.

    With a `jax.sharding.Sharding` (e.g. NamedSharding over a data axis),
    each batch is placed sharded across the mesh.  With no explicit
    sharding but a process default mesh declared
    (`ray_tpu.parallel.set_default_mesh`), batches land batch-sharded
    over its data axes — the Data->Train hot path needs no per-callsite
    sharding plumbing.  Otherwise batches go to the default device.

    The auto path only engages when every mesh device is addressable
    from this process: in multi-process SPMD each worker iterates its
    OWN data shard, and a device_put onto a global mesh would treat the
    local batch as the (assumed process-identical) global array —
    silently assembling an incoherent mix.  SPMD callers pass an
    explicit sharding (or build global arrays with
    jax.make_array_from_process_local_data).
    """
    # mesh capture happens NOW, at call time — inside a generator body it
    # would be deferred to the first next(), after a `with default_mesh`
    # block may already have exited
    auto_sharding, auto_divisor = None, 1
    if sharding is None:
        from ray_tpu.parallel import data_axes, get_default_mesh

        mesh = get_default_mesh()
        if mesh is not None:
            import jax as _jax
            import math

            from jax.sharding import NamedSharding, PartitionSpec

            pidx = _jax.process_index()
            addressable = all(d.process_index == pidx
                              for d in mesh.devices.flat)
            # batch (dim 0) over the mesh's data axes; trailing dims stay
            # unsharded so 1-D labels and N-D images both place cleanly
            axes = tuple(a for a in data_axes(mesh)
                         if mesh.shape.get(a, 1) > 1)
            if axes and addressable:
                auto_sharding = NamedSharding(mesh, PartitionSpec(axes))
                auto_divisor = math.prod(mesh.shape[a] for a in axes)
    return _iter_jax_batches_inner(batch_iter, sharding, auto_sharding,
                                   auto_divisor, dtypes, prefetch)


def _iter_jax_batches_inner(batch_iter, sharding, auto_sharding,
                            auto_divisor, dtypes, prefetch):
    import jax

    def put(batch):
        def place(x, dtype=None):
            arr = np.asarray(x)
            if dtype is not None:
                arr = arr.astype(dtype)
            if sharding is not None:
                return jax.device_put(arr, sharding)
            if auto_sharding is not None and arr.ndim >= 1 \
                    and arr.shape[0] % auto_divisor == 0:
                # indivisible batches (e.g. a short final batch) take the
                # default-device path instead of crashing the iterator
                return jax.device_put(arr, auto_sharding)
            return jax.device_put(arr)

        if isinstance(batch, dict):
            out = {k: place(v, dtypes.get(k) if dtypes else None)
                   for k, v in batch.items()}
        else:
            out = place(batch, dtypes if not isinstance(dtypes, dict)
                        else None)
        return out

    buf: collections.deque = collections.deque()
    it = iter(batch_iter)
    # fill the pipeline
    try:
        for _ in range(max(1, prefetch)):
            buf.append(put(next(it)))
    except StopIteration:
        pass
    for batch in it:
        nxt = put(batch)  # enqueue transfer for N+1 before yielding N
        yield buf.popleft()
        buf.append(nxt)
    while buf:
        yield buf.popleft()


# ---------------------------------------------------------------------------
# DataIterator: a shardable batch-iteration handle (reference:
# python/ray/data/iterator.py DataIterator + _StreamingIterator). Train
# workers receive these — they must serialize into actor tasks.


class DataIterator:
    """Batch iteration over a stream of blocks; see Dataset.iterator()
    and Dataset.streaming_split()."""

    def _block_iter(self):
        raise NotImplementedError

    def iter_rows(self):
        for block in self._block_iter():
            yield from BlockAccessor(block).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: Optional[str] = None,
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: Optional[int] = None):
        ctx = DataContext.get_current()
        fmt = batch_format or ctx.default_batch_format
        it = iter_block_batches(
            self._block_iter(), batch_size=batch_size, batch_format=fmt,
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            seed=local_shuffle_seed)
        depth = (ctx.prefetch_batches if prefetch_batches is None
                 else prefetch_batches)
        return prefetch_iter(it, depth) if depth else it

    def iter_torch_batches(self, **kw):
        import torch

        for b in self.iter_batches(batch_format="numpy", **kw):
            yield {k: torch.as_tensor(v) for k, v in b.items()}

    def iter_jax_batches(self, **kw):
        sharding = kw.pop("sharding", None)
        dtypes = kw.pop("dtypes", None)
        prefetch = kw.pop("prefetch", 2)
        return iter_jax_batches(
            self.iter_batches(batch_format="numpy", **kw),
            sharding=sharding, dtypes=dtypes, prefetch=prefetch)


class _DatasetIterator(DataIterator):
    """Iterator over a full Dataset (Dataset.iterator())."""

    def __init__(self, ds):
        self._ds = ds

    def _block_iter(self):
        for bundle in self._ds.iter_internal_ref_bundles():
            yield ray_tpu.get(bundle.block_ref, timeout=600)


class _SplitCoordinator:
    """Actor executing one Dataset stream per epoch and serving its output
    blocks to n consumers (reference: _internal/execution/streaming_executor
    -> StreamSplitDataIterator coordinator actor).  Iterating a shard again
    is a new epoch: the stream re-executes once EVERY split finished the
    previous epoch (SPMD consumers iterate in lockstep, like the
    reference's split coordinator epoch barrier)."""

    def __init__(self, ds, n: int, equal: bool):
        import asyncio

        self._ds = ds
        self._n = n
        self._equal = equal
        self._epoch = -1      # no epoch started yet
        self._done = set()    # splits finished with the current epoch
        self._gen = None
        self._gen_lock = asyncio.Lock()
        self._start_task = None
        self._static = None   # equal=True: per-split block ref deques
        # pin only a bounded in-flight window of served refs: consumers
        # fetch a block right after receiving its ref, and pinning the
        # whole epoch would hold the entire dataset in the object store
        self._served = collections.deque(maxlen=64)

    PARK_S = 20.0  # max server-side park per call (client just re-calls)

    def _materialize_epoch(self):
        """Runs in a worker thread (to_thread): equal=True materializes
        the whole dataset; streaming just builds the generator."""
        if self._equal:
            splits = self._ds.split(self._n, equal=True)
            return [collections.deque(s.get_internal_block_refs())
                    for s in splits]
        return iter(self._ds.iter_internal_ref_bundles())

    async def next_block_ref(self, split_idx: int, epoch: int):
        """{"ref": r} | {"end": True} | {"wait": True}.  Barrier and
        epoch-start waits park HERE on the actor's event loop (async
        actor: calls interleave at awaits) for up to PARK_S — the client
        re-calls on {"wait"}, so no per-call timeout ever has to cover an
        unboundedly slow peer or a long epoch materialization.  State is
        loop-thread-confined; mutations only between awaits."""
        import asyncio

        loop = asyncio.get_event_loop()
        t0 = loop.time()
        while True:
            if epoch > self._epoch:
                if self._epoch >= 0 and len(self._done) < self._n:
                    # some split is still consuming the previous epoch
                    if loop.time() - t0 > self.PARK_S:
                        return {"wait": True}
                    await asyncio.sleep(0.02)
                    continue
                if self._start_task is None:
                    self._start_task = asyncio.ensure_future(
                        asyncio.to_thread(self._materialize_epoch))
                if not self._start_task.done():
                    if loop.time() - t0 > self.PARK_S:
                        return {"wait": True}
                    await asyncio.sleep(0.02)
                    continue
                task, self._start_task = self._start_task, None
                payload = task.result()  # raises the materialization error
                self._epoch = epoch
                self._done = set()
                self._served = collections.deque(maxlen=64)
                if self._equal:
                    self._static = payload
                else:
                    self._gen = payload
            elif epoch < self._epoch or split_idx in self._done:
                return {"end": True}
            if self._equal:
                q = self._static[split_idx]
                if not q:
                    self._done.add(split_idx)
                    return {"end": True}
                ref = q.popleft()
                self._served.append(ref)
                return {"ref": ref}
            async with self._gen_lock:
                gen = self._gen
                # sentinel form: a raw StopIteration cannot cross an
                # executor Future boundary
                bundle = await asyncio.to_thread(next, gen, None)
            if bundle is None:
                self._done.add(split_idx)
                return {"end": True}
            self._served.append(bundle)
            return {"ref": bundle.block_ref}

    async def finish_epoch(self, split_idx: int, epoch: int):
        """Consumer stopped iterating (exhausted OR abandoned mid-epoch) —
        count it toward the epoch barrier either way."""
        if epoch == self._epoch:
            self._done.add(split_idx)
        return True


class _StreamSplitIterator(DataIterator):
    """One shard of Dataset.streaming_split; safe to ship to an actor.
    Each full iteration is one epoch of the underlying stream."""

    def __init__(self, coordinator, split_idx: int):
        self._coord = coordinator
        self._idx = split_idx
        self._epoch = 0

    def _block_iter(self):
        epoch = self._epoch
        self._epoch += 1
        try:
            while True:
                r = ray_tpu.get(
                    self._coord.next_block_ref.remote(self._idx, epoch),
                    timeout=600)
                if r.get("wait"):
                    continue  # server parked PARK_S; just ask again
                if r.get("end"):
                    return
                yield ray_tpu.get(r["ref"], timeout=600)
        finally:
            try:
                self._coord.finish_epoch.remote(self._idx, epoch)
            except Exception:
                pass
