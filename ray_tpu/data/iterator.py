"""Batch iteration, including device-fed iteration for TPU training.

Reference: python/ray/data/iterator.py (iter_batches, iter_torch_batches).
TPU-native twist: ``iter_jax_batches`` stages host batches into HBM with
double buffering — ``jax.device_put`` of batch N+1 is issued while batch N
is being consumed, so input feeding overlaps the device step (the role the
reference delegates to torch DataLoader pinned-memory prefetch).
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu

from .block import BlockAccessor
from .context import DataContext


def iter_block_batches(block_iter, *, batch_size: Optional[int],
                       batch_format: str, drop_last: bool = False,
                       local_shuffle_buffer_size: Optional[int] = None,
                       seed: Optional[int] = None):
    """Re-batch a stream of blocks into fixed-size batches."""
    if local_shuffle_buffer_size:
        yield from _iter_shuffled_batches(
            block_iter, batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last, buffer_size=local_shuffle_buffer_size,
            seed=seed)
        return
    carry = None  # carry-over arrow table smaller than batch_size

    def emit(table):
        return BlockAccessor(table).to_batch(batch_format)

    for block in block_iter:
        acc = BlockAccessor(block)
        if acc.num_rows() == 0:
            continue
        table = acc.to_arrow()
        if carry is not None:
            table = BlockAccessor.concat([carry, table])
            carry = None
        if batch_size is None:
            yield emit(table)
            continue
        n = table.num_rows
        start = 0
        while n - start >= batch_size:
            yield emit(table.slice(start, batch_size))
            start += batch_size
        if start < n:
            carry = table.slice(start)
    if carry is not None and not drop_last:
        yield emit(carry)


def _iter_shuffled_batches(block_iter, *, batch_size, batch_format,
                           drop_last, buffer_size, seed):
    """Local shuffle: rows pool in a buffer that mixes ACROSS blocks; once
    the pool holds >= buffer_size + batch_size rows it is permuted and
    batches are drawn from it (reference: iterator's
    local_shuffle_buffer_size contract — a bigger buffer means more
    mixing)."""
    rng = np.random.RandomState(seed)
    bs = batch_size or int(buffer_size)
    buf = None

    def emit(table):
        return BlockAccessor(table).to_batch(batch_format)

    def permute(table):
        return BlockAccessor(table).random_permutation(
            int(rng.randint(0, 2**31)))

    for block in block_iter:
        acc = BlockAccessor(block)
        if acc.num_rows() == 0:
            continue
        t = acc.to_arrow()
        buf = t if buf is None else BlockAccessor.concat([buf, t])
        if buf.num_rows >= buffer_size + bs:
            buf = permute(buf)
            while buf.num_rows >= buffer_size + bs:
                yield emit(buf.slice(0, bs))
                buf = buf.slice(bs)
    if buf is not None and buf.num_rows:
        buf = permute(buf)
        start = 0
        while buf.num_rows - start >= bs:
            yield emit(buf.slice(start, bs))
            start += bs
        if start < buf.num_rows and not drop_last:
            yield emit(buf.slice(start))


def prefetch_iter(it: Iterator, depth: int) -> Iterator:
    """Run `it` in a background thread with a bounded queue."""
    if depth <= 0:
        yield from it
        return
    q: queue.Queue = queue.Queue(maxsize=depth)
    DONE = object()
    err: List[BaseException] = []

    def worker():
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # propagate to consumer
            err.append(e)
        finally:
            q.put(DONE)

    t = threading.Thread(target=worker, daemon=True, name="data-prefetch")
    t.start()
    while True:
        item = q.get()
        if item is DONE:
            if err:
                raise err[0]
            return
        yield item


def iter_jax_batches(batch_iter: Iterator[Dict[str, np.ndarray]], *,
                     sharding=None, dtypes: Optional[Dict[str, Any]] = None,
                     prefetch: int = 2) -> Iterator:
    """Move numpy batches onto device with double buffering.

    With a `jax.sharding.Sharding` (e.g. NamedSharding over a data axis),
    each batch is placed sharded across the mesh; otherwise it goes to the
    default device.
    """
    import jax

    def put(batch):
        def place(x, dtype=None):
            arr = np.asarray(x)
            if dtype is not None:
                arr = arr.astype(dtype)
            if sharding is not None:
                return jax.device_put(arr, sharding)
            return jax.device_put(arr)

        if isinstance(batch, dict):
            out = {k: place(v, dtypes.get(k) if dtypes else None)
                   for k, v in batch.items()}
        else:
            out = place(batch, dtypes if not isinstance(dtypes, dict)
                        else None)
        return out

    buf: collections.deque = collections.deque()
    it = iter(batch_iter)
    # fill the pipeline
    try:
        for _ in range(max(1, prefetch)):
            buf.append(put(next(it)))
    except StopIteration:
        pass
    for batch in it:
        nxt = put(batch)  # enqueue transfer for N+1 before yielding N
        yield buf.popleft()
        buf.append(nxt)
    while buf:
        yield buf.popleft()
