"""Dependency-free Avro Object Container File codec.

Reference: python/ray/data/read_api.py read_avro delegates to fastavro;
that library is not bundled here, so — like the TFRecord/Example codec
in datasource.py — the container format and binary encoding are
implemented directly from the Avro 1.11 spec:

  file   = magic "Obj\\x01" + metadata map (avro.schema JSON,
           avro.codec) + 16-byte sync marker + blocks
  block  = long(count) + long(byte_size) + records + sync marker
  codec  = null | deflate (raw zlib, no header)

Binary encoding: zigzag-varint longs, length-prefixed bytes/strings,
IEEE754 LE float/double, 1-byte booleans, block-encoded arrays/maps,
union by branch index, records in field order.

Schema support covers the shapes tabular data actually uses: primitives,
records, arrays, maps, unions (for nullable columns), enums and fixed.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, List, Tuple

MAGIC = b"Obj\x01"

# ---------------------------------------------------------------------------
# binary primitives


def _w_long(out: io.BytesIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63)             # zigzag
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes((b | 0x80,)))
        else:
            out.write(bytes((b,)))
            return


def _r_long(buf: memoryview, pos: int) -> Tuple[int, int]:
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos  # un-zigzag


def _w_bytes(out: io.BytesIO, b: bytes) -> None:
    _w_long(out, len(b))
    out.write(b)


def _r_bytes(buf: memoryview, pos: int) -> Tuple[bytes, int]:
    n, pos = _r_long(buf, pos)
    return bytes(buf[pos:pos + n]), pos + n


# ---------------------------------------------------------------------------
# schema-driven encode/decode


def _holds_null(schema: Any) -> bool:
    """Whether `schema` admits null: bare "null" (str or dict form — what
    inference emits for all-None columns) or a union with a null branch
    in either spelling."""
    if isinstance(schema, list):
        return any(_holds_null(b) for b in schema)
    t = schema.get("type") if isinstance(schema, dict) else schema
    return t == "null"


def _write_datum(out: io.BytesIO, schema: Any, v: Any) -> None:
    if isinstance(schema, list):             # union: pick the branch
        for i, branch in enumerate(schema):
            if _matches(branch, v):
                _w_long(out, i)
                _write_datum(out, branch, v)
                return
        # coercion pass: the non-union writers widen (double accepts
        # int, string str()-s anything) — the union path must accept the
        # same values or nullable columns crash where plain ones don't
        for i, branch in enumerate(schema):
            if _coercible(branch, v):
                _w_long(out, i)
                _write_datum(out, branch, v)
                return
        raise TypeError(f"value {v!r} matches no union branch {schema}")
    t = schema["type"] if isinstance(schema, dict) else schema
    if isinstance(t, (dict, list)):         # {"type": <schema>} wrapper
        _write_datum(out, t, v)
        return
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if v else b"\x00")
    elif t in ("int", "long"):
        _w_long(out, int(v))
    elif t == "float":
        out.write(struct.pack("<f", float(v)))
    elif t == "double":
        out.write(struct.pack("<d", float(v)))
    elif t == "bytes":
        _w_bytes(out, bytes(v))
    elif t == "string":
        _w_bytes(out, str(v).encode())
    elif t == "record":
        for f in schema["fields"]:
            ft = f["type"]
            if _holds_null(ft):
                # nullable field: a missing key writes null (inference
                # marks absent-anywhere columns nullable)
                _write_datum(out, ft, v.get(f["name"]))
            else:
                # required field: a missing key must RAISE (KeyError),
                # not silently write "None"/False through coercion
                _write_datum(out, ft, v[f["name"]])
    elif t == "array":
        items = list(v)
        if items:
            _w_long(out, len(items))
            for item in items:
                _write_datum(out, schema["items"], item)
        _w_long(out, 0)
    elif t == "map":
        if v:
            _w_long(out, len(v))
            for k, mv in v.items():
                _w_bytes(out, str(k).encode())
                _write_datum(out, schema["values"], mv)
        _w_long(out, 0)
    elif t == "enum":
        _w_long(out, schema["symbols"].index(v))
    elif t == "fixed":
        out.write(bytes(v))
    else:
        raise TypeError(f"unsupported avro type {t!r}")


def _matches(schema: Any, v: Any) -> bool:
    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return v is None
    if t == "boolean":
        return isinstance(v, bool)
    if t in ("int", "long"):
        return isinstance(v, int) and not isinstance(v, bool)
    if t in ("float", "double"):
        return isinstance(v, float)
    if t == "bytes":
        return isinstance(v, (bytes, bytearray))
    if t == "string":
        return isinstance(v, str)
    if t == "record":
        return isinstance(v, dict)
    if t == "array":
        return isinstance(v, (list, tuple))
    if t == "map":
        return isinstance(v, dict)
    return v is not None


def _coercible(schema: Any, v: Any) -> bool:
    t = schema["type"] if isinstance(schema, dict) else schema
    if t in ("float", "double"):
        return isinstance(v, (int, float)) and not isinstance(v, bool)
    if t == "string":
        return v is not None
    return False


def _resolve_named(schema: Any, names: Dict[str, Any] = None) -> Any:
    """Replace references to previously defined named types (record/enum/
    fixed, Avro spec §Names) with their definition dicts, in schema-DFS
    order.  Iceberg manifest schemas reference the partition record type
    by name (e.g. "r102"), so the registry is required to read them.
    Replacement is by shared reference, which keeps recursive record
    schemas (linked-list shapes) well-defined."""
    if names is None:
        names = {}
    if isinstance(schema, str):
        return names.get(schema, schema)
    if isinstance(schema, list):
        return [_resolve_named(s, names) for s in schema]
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed", "error"):
            nm = schema.get("name")
            if nm:
                names[nm] = schema
                ns = schema.get("namespace")
                if ns:
                    names[f"{ns}.{nm}"] = schema
            if t == "record":
                for f in schema.get("fields", ()):
                    f["type"] = _resolve_named(f["type"], names)
        elif t == "array":
            schema["items"] = _resolve_named(schema.get("items"), names)
        elif t == "map":
            schema["values"] = _resolve_named(schema.get("values"), names)
        elif isinstance(t, (dict, list, str)):
            schema["type"] = _resolve_named(t, names)
    return schema


def _read_datum(buf: memoryview, pos: int, schema: Any) -> Tuple[Any, int]:
    if isinstance(schema, list):
        i, pos = _r_long(buf, pos)
        return _read_datum(buf, pos, schema[i])
    t = schema["type"] if isinstance(schema, dict) else schema
    if isinstance(t, (dict, list)):   # {"type": <schema>} wrapper
        return _read_datum(buf, pos, t)
    if t == "null":
        return None, pos
    if t == "boolean":
        return buf[pos] != 0, pos + 1
    if t in ("int", "long"):
        return _r_long(buf, pos)
    if t == "float":
        return struct.unpack_from("<f", buf, pos)[0], pos + 4
    if t == "double":
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if t == "bytes":
        return _r_bytes(buf, pos)
    if t == "string":
        b, pos = _r_bytes(buf, pos)
        return b.decode(), pos
    if t == "record":
        rec = {}
        for f in schema["fields"]:
            rec[f["name"]], pos = _read_datum(buf, pos, f["type"])
        return rec, pos
    if t == "array":
        items: List[Any] = []
        while True:
            n, pos = _r_long(buf, pos)
            if n == 0:
                return items, pos
            if n < 0:                        # block with byte size
                n = -n
                _, pos = _r_long(buf, pos)
            for _ in range(n):
                item, pos = _read_datum(buf, pos, schema["items"])
                items.append(item)
    if t == "map":
        m: Dict[str, Any] = {}
        while True:
            n, pos = _r_long(buf, pos)
            if n == 0:
                return m, pos
            if n < 0:
                n = -n
                _, pos = _r_long(buf, pos)
            for _ in range(n):
                k, pos = _r_bytes(buf, pos)
                m[k.decode()], pos = _read_datum(buf, pos, schema["values"])
    if t == "enum":
        i, pos = _r_long(buf, pos)
        return schema["symbols"][i], pos
    if t == "fixed":
        n = schema["size"]
        return bytes(buf[pos:pos + n]), pos + n
    raise TypeError(f"unsupported avro type {t!r}")


# ---------------------------------------------------------------------------
# container file


def container_schema(data: bytes) -> Dict[str, Any]:
    """The schema JSON embedded in a container file's header, verbatim."""
    buf = memoryview(data)
    if bytes(buf[:4]) != MAGIC:
        raise ValueError("not an Avro object container file")
    pos = 4
    while True:
        n, pos = _r_long(buf, pos)
        if n == 0:
            break
        if n < 0:
            n = -n
            _, pos = _r_long(buf, pos)
        for _ in range(n):
            k, pos = _r_bytes(buf, pos)
            v, pos = _r_bytes(buf, pos)
            if k == b"avro.schema":
                return json.loads(v)
    raise ValueError("container file has no avro.schema header")


def read_container(data: bytes) -> List[Dict[str, Any]]:
    """All records of one Object Container File."""
    buf = memoryview(data)
    if bytes(buf[:4]) != MAGIC:
        raise ValueError("not an Avro object container file")
    pos = 4
    meta: Dict[str, bytes] = {}
    while True:
        n, pos = _r_long(buf, pos)
        if n == 0:
            break
        if n < 0:
            n = -n
            _, pos = _r_long(buf, pos)
        for _ in range(n):
            k, pos = _r_bytes(buf, pos)
            meta[k.decode()], pos = _r_bytes(buf, pos)
    schema = _resolve_named(json.loads(meta["avro.schema"]))
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    sync = bytes(buf[pos:pos + 16])
    pos += 16
    records: List[Dict[str, Any]] = []
    while pos < len(buf):
        count, pos = _r_long(buf, pos)
        size, pos = _r_long(buf, pos)
        block = bytes(buf[pos:pos + size])
        pos += size
        if bytes(buf[pos:pos + 16]) != sync:
            raise ValueError("avro sync marker mismatch (corrupt file)")
        pos += 16
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        bview = memoryview(block)
        bpos = 0
        for _ in range(count):
            rec, bpos = _read_datum(bview, bpos, schema)
            records.append(rec)
    return records


def _infer_schema(rows: List[Dict[str, Any]], name: str = "row") -> Dict:
    """Record schema from sample rows; columns that ever hold None become
    nullable unions."""
    fields = []
    cols: Dict[str, set] = {}
    present: Dict[str, int] = {}
    for r in rows:
        for k, v in r.items():
            cols.setdefault(k, set()).add(_type_of(v))
            present[k] = present.get(k, 0) + 1
    for k, types in cols.items():
        # nullable if any row held None OR lacked the column entirely
        nullable = "null" in types or present[k] < len(rows)
        types.discard("null")
        if not types:
            t: Any = "null"
        elif len(types) == 1:
            t = next(iter(types))
        else:
            # mixed int/float widens to double; else a union
            t = "double" if types <= {"long", "double"} else sorted(types)
        if nullable and t != "null":
            # flatten: unions may not nest unions (Avro spec) — a
            # nullable mixed-type column is ["null", a, b], never
            # ["null", [a, b]]
            t = ["null"] + (t if isinstance(t, list) else [t])
        fields.append({"name": k, "type": t})
    return {"type": "record", "name": name, "fields": fields}


def _type_of(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, int):
        return "long"
    if isinstance(v, float):
        return "double"
    if isinstance(v, (bytes, bytearray)):
        return "bytes"
    if isinstance(v, str):
        return "string"
    if isinstance(v, (list, tuple)):
        return "string"  # stringified fallback for nested lists
    return "string"


def _plain(v: Any) -> Any:
    """Numpy scalars/arrays -> python values (block rows carry them)."""
    if isinstance(v, (bytes, bytearray, str)) or v is None:
        return v
    item = getattr(v, "item", None)
    if item is not None and getattr(v, "ndim", 0) == 0:
        return v.item()
    tolist = getattr(v, "tolist", None)
    if tolist is not None:
        return v.tolist()
    return v


def write_container(rows: List[Dict[str, Any]], *, schema: Dict = None,
                    codec: str = "null") -> bytes:
    """Rows -> one Object Container File (schema inferred if absent)."""
    rows = [{k: _plain(v) for k, v in r.items()} for r in rows]
    schema = schema or _infer_schema(rows)
    # embed the schema as given (named refs stay refs — re-dumping the
    # resolved form would illegally redefine named types), but encode
    # datums against the resolved view
    schema_json = json.dumps(schema)
    schema = _resolve_named(json.loads(schema_json))
    body = io.BytesIO()
    for r in rows:
        _write_datum(body, schema, r)
    block = body.getvalue()
    if codec == "deflate":
        c = zlib.compressobj(wbits=-15)
        block = c.compress(block) + c.flush()
    elif codec != "null":
        raise ValueError(f"unsupported avro codec {codec!r}")
    sync = os.urandom(16)
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": schema_json.encode(),
            "avro.codec": codec.encode()}
    _w_long(out, len(meta))
    for k, v in meta.items():
        _w_bytes(out, k.encode())
        _w_bytes(out, v)
    _w_long(out, 0)
    out.write(sync)
    _w_long(out, len(rows))
    _w_long(out, len(block))
    out.write(block)
    out.write(sync)
    return out.getvalue()
