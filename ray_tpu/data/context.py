"""Execution context / knobs for ray_tpu.data.

Equivalent of the reference's DataContext (reference:
python/ray/data/context.py) — a process-wide singleton of execution
options consulted at plan/execution time.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class DataContext:
    # Target size for blocks produced by reads and maps (bytes).
    target_max_block_size: int = 128 * 1024 * 1024
    # Shuffle ops aim for this many output partitions when not specified.
    default_shuffle_partitions: Optional[int] = None
    # Streaming executor: global cap on concurrently in-flight tasks.
    max_concurrent_tasks: int = 16
    # Per-operator cap on in-flight tasks (None = no per-op cap).
    max_tasks_per_operator: Optional[int] = None
    # Backpressure: pause upstream submission when this many output bundles
    # are buffered but not yet consumed (reference: backpressure policies in
    # python/ray/data/_internal/execution/backpressure_policy/).
    max_buffered_output_bundles: int = 32
    # Default batch format for map_batches / iter_batches.
    default_batch_format: str = "numpy"
    # iter_batches prefetch depth (batches).
    prefetch_batches: int = 2
    # Whether to eagerly free consumed intermediate blocks.
    eager_free: bool = True
    # Seed used by random_shuffle / random_sample when not given.
    seed: Optional[int] = None
    # Extra resources to attach to data tasks.
    task_resources: Dict[str, float] = field(default_factory=dict)
    # Stream blocks out of read/map tasks as they are produced instead of
    # buffering whole task outputs (reference: streaming generator returns
    # in the streaming executor); bounds per-task memory.
    use_streaming_generators: bool = True
    # Emit output bundles in dataset order (take/iter_rows return the
    # FIRST rows; tasks still run fully parallel — only the final yield
    # is sequenced).  False trades order for lower first-output latency
    # (reference: ExecutionOptions.preserve_order).
    preserve_order: bool = True
    # Max unconsumed streamed items (block+meta pairs count as 2) before
    # the producing task pauses (reference:
    # _generator_backpressure_num_objects).
    generator_backpressure_num_objects: int = 8

    _lock = threading.Lock()
    _current: Optional["DataContext"] = None

    @staticmethod
    def get_current() -> "DataContext":
        with DataContext._lock:
            if DataContext._current is None:
                DataContext._current = DataContext()
            return DataContext._current
