"""External-service datasources: BigQuery and MongoDB.

reference: python/ray/data/read_api.py read_bigquery (:523) and
read_mongo (:423), python/ray/data/datasource/{bigquery,mongo}_datasource.py.

Both are written against DUCK-TYPED clients injected via
``client_factory`` (the same pattern as the GBDT/W&B shims): production
passes nothing and the real client library is imported lazily; tests
pass a fake with the same method surface and never touch the service.

Client surfaces consumed:

BigQuery (google.cloud.bigquery[_storage] shape):
  * query path:  client.query(sql).to_arrow() -> pyarrow.Table
  * table path:  client.create_read_session(table=..., max_stream_count=N)
                   -> session with .streams (list of objects with .name)
                      and optionally .estimated_row_count
                 client.read_rows(stream_name).to_arrow() -> pyarrow.Table

Mongo (pymongo shape):
  * client_factory(uri) -> client;  client[db][coll]
  * coll.estimated_document_count() -> int (plan-time metadata)
  * coll.aggregate(pipeline) -> iterable of dict rows
    (partitioned reads append $skip/$limit stages per task)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from .datasource import (BlockMetadata, Datasource, ReadTask,
                         rows_to_block)


class BigQueryDatasource(Datasource):
    """Table reads fan out over the storage API's read streams (one
    read task per stream, the reference's parallelism unit); query
    reads run the query as one task (BigQuery parallelizes the query
    itself server-side)."""

    def __init__(self, project_id: str, dataset: Optional[str] = None,
                 query: Optional[str] = None,
                 client_factory: Optional[Callable[[], Any]] = None):
        if (dataset is None) == (query is None):
            raise ValueError("read_bigquery: pass exactly one of "
                             "dataset= ('dataset.table') or query=")
        self._project = project_id
        self._dataset = dataset
        self._query = query
        self._factory = client_factory or _default_bigquery_client
        self._session = None

    def _meta_session(self):
        """Lazy plan-time metadata session (one control call, no scan):
        constructing a never-executed lazy Dataset must not hit the
        network."""
        if self._session is None and self._query is None:
            self._session = self._factory().create_read_session(
                table=f"{self._project}.{self._dataset}",
                max_stream_count=0)
        return self._session

    def get_name(self) -> str:
        return "BigQuery"

    def plan_row_count(self) -> Optional[int]:
        # the session's row count is an ESTIMATE; the base contract
        # (datasource.py: "only return a number that is guaranteed
        # exact — Dataset.count() trusts it") forbids returning it
        return None

    def estimated_row_count(self) -> Optional[int]:
        n = getattr(self._meta_session(), "estimated_row_count", None)
        return int(n) if n is not None else None

    def estimate_inmemory_data_size(self) -> Optional[int]:
        n = getattr(self._meta_session(), "estimated_total_bytes", None)
        return int(n) if n is not None else None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        factory = self._factory
        if self._query is not None:
            query = self._query

            def read_query():
                yield factory().query(query).to_arrow()

            return [ReadTask(read_query,
                             BlockMetadata(num_rows=0, size_bytes=0))]

        client = factory()
        session = client.create_read_session(
            table=f"{self._project}.{self._dataset}",
            max_stream_count=max(1, parallelism))
        streams = list(getattr(session, "streams", []) or [])
        if not streams:
            return []
        est = getattr(session, "estimated_row_count", None)
        per = int(est) // len(streams) if est else 0

        def make(stream_name):
            def read():
                yield factory().read_rows(stream_name).to_arrow()
            return read

        return [ReadTask(make(getattr(s, "name", s)),
                         BlockMetadata(num_rows=per, size_bytes=0))
                for s in streams]


class MongoDatasource(Datasource):
    """Partitioned collection reads: each task runs the caller's
    aggregation pipeline with an appended $skip/$limit window (the
    windows tile the collection; MongoDB executes each server-side)."""

    def __init__(self, uri: str, database: str, collection: str,
                 pipeline: Optional[List[Dict]] = None,
                 client_factory: Optional[Callable[[str], Any]] = None):
        self._uri = uri
        self._db = database
        self._coll = collection
        self._pipeline = list(pipeline or [])
        self._factory = client_factory or _default_mongo_client
        coll = self._factory(uri)[database][collection]
        self._count = int(coll.estimated_document_count())

    def get_name(self) -> str:
        return "Mongo"

    def plan_row_count(self) -> Optional[int]:
        # estimated_document_count is metadata-fast but NOT exact (stale
        # after unclean shutdowns, sharded clusters) — the base contract
        # requires exactness, so planning gets None and count() scans
        return None

    def estimated_row_count(self) -> Optional[int]:
        return self._count if not self._pipeline else None

    #: pipeline stages after which document order is the stage's own
    #: (or undefined) — a leading _id sort no longer pins the windows
    _ORDER_DESTROYING = {"$sort", "$group", "$sample", "$unionWith",
                         "$unwind", "$project", "$unset",
                         "$replaceRoot", "$replaceWith"}

    def _partitionable(self) -> bool:
        return not any(set(st) & self._ORDER_DESTROYING
                       for st in self._pipeline)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        uri, db, coll_name = self._uri, self._db, self._coll
        pipeline, factory = self._pipeline, self._factory

        # Partitioned windows need a stable document order.  A LEADING
        # `$sort: {_id: 1}` walks the _id index (cheap, no in-memory
        # sort) and $match after it preserves order; pipelines with
        # order-destroying or _id-dropping stages can't be windowed
        # safely and fall back to ONE task (correct, not parallel —
        # the reference partitions on _id ranges with the same caveat).
        if not self._partitionable():
            def read_single():
                coll = factory(uri)[db][coll_name]
                rows = [{k: v for k, v in r.items() if k != "_id"}
                        for r in coll.aggregate(list(pipeline))]
                yield rows_to_block(rows)

            return [ReadTask(read_single,
                             BlockMetadata(num_rows=0, size_bytes=0))]

        n_tasks = max(1, min(parallelism, self._count or 1))
        base = (self._count // n_tasks) if self._count else 0
        tasks = []
        for i in range(n_tasks):
            skip = i * base
            # the last window is unbounded: estimated_document_count can
            # undercount a live collection, and rows must not be dropped
            limit = base if i < n_tasks - 1 else None

            def make(skip=skip, limit=limit):
                def read():
                    stages = [{"$sort": {"_id": 1}}, *pipeline,
                              {"$skip": skip}]
                    if limit is not None:
                        stages.append({"$limit": limit})
                    coll = factory(uri)[db][coll_name]
                    rows = [{k: v for k, v in r.items() if k != "_id"}
                            for r in coll.aggregate(stages)]
                    yield rows_to_block(rows)
                return read

            tasks.append(ReadTask(make(), BlockMetadata(
                num_rows=base if limit is not None else 0, size_bytes=0)))
        return tasks


def _default_bigquery_client():
    """Adapt the real google clients to the duck surface this module
    consumes (BigQueryReadClient's native API takes parent/proto args,
    not table strings, and queries live on a different client).  This
    adapter necessarily runs only where the google libraries exist —
    the gated environments the connectors exist for."""
    try:
        from google.cloud import bigquery  # type: ignore
        from google.cloud import bigquery_storage  # type: ignore
    except ImportError as e:
        raise ImportError(
            "read_bigquery requires google-cloud-bigquery[-storage] (not "
            "available in this environment) — or pass client_factory= "
            "with a compatible client") from e

    class _GoogleAdapter:
        def __init__(self):
            self._bq = bigquery.Client()
            self._storage = bigquery_storage.BigQueryReadClient()

        def query(self, sql):
            return self._bq.query(sql).result()   # RowIterator.to_arrow()

        def create_read_session(self, table, max_stream_count=0):
            project, dataset, tbl = table.split(".", 2)
            from google.cloud.bigquery_storage import types

            session = types.ReadSession(
                table=f"projects/{project}/datasets/{dataset}"
                      f"/tables/{tbl}",
                data_format=types.DataFormat.ARROW)
            return self._storage.create_read_session(
                parent=f"projects/{project}", read_session=session,
                max_stream_count=max_stream_count)

        def read_rows(self, stream_name):
            reader = self._storage.read_rows(stream_name)

            class _Rows:
                def to_arrow(self):
                    import pyarrow as pa

                    rows = reader.rows()
                    if hasattr(rows, "to_arrow"):
                        return rows.to_arrow()
                    return pa.Table.from_batches(
                        [p.to_arrow() for p in rows.pages])

            return _Rows()

    return _GoogleAdapter()


def _default_mongo_client(uri: str):
    try:
        import pymongo  # type: ignore
    except ImportError as e:
        raise ImportError(
            "read_mongo requires pymongo (not available in this "
            "environment) — or pass client_factory= with a compatible "
            "client") from e
    return pymongo.MongoClient(uri)
