"""Native lakehouse table IO: Delta Lake and Apache Iceberg readers.

The reference exposes lakehouse tables through client libraries
(reference: python/ray/data/read_api.py read_delta_sharing_tables,
read_iceberg via pyiceberg, read_databricks_tables); none of those
libraries are bundled here, and a TPU pod reading training data from
object storage cannot shell out to a JVM.  So the table formats are
implemented directly from their specs, on top of the scheme-dispatched
fileio layer (local paths or any fsspec URI):

Delta Lake (protocol spec: github.com/delta-io/delta/blob/master/PROTOCOL.md)
  table/_delta_log/00000000000000000000.json   commit: JSON action lines
  table/_delta_log/<v>.checkpoint.parquet      state snapshot at version v
  table/_delta_log/_last_checkpoint            pointer to latest checkpoint
  Snapshot = replay adds/removes from the newest usable checkpoint through
  the target version.  Partition values live in the log, NOT the data
  files, so they are grafted onto each block as constant columns.

Iceberg (spec: iceberg.apache.org/spec/)
  table/metadata/v<N>.metadata.json (or <seq>-<uuid>.metadata.json)
    -> snapshots[current-snapshot-id].manifest-list   (avro)
    -> manifest_file.manifest_path                    (avro)
    -> manifest_entry.data_file.file_path             (parquet or avro)
  Manifests are Avro container files read with the dependency-free codec
  in _avro.py (named-type references included).  Iceberg stores partition
  columns inside the data files, so no column grafting is needed.

Both readers surface row counts at plan time (Delta: add.stats numRecords;
Iceberg: data_file.record_count) so the optimizer can size-split reads the
same way the parquet metadata provider does.
"""

from __future__ import annotations

import json
import os
import re
import urllib.parse
from typing import Any, Dict, List, Optional

from .datasource import Datasource, ReadTask
from .block import Block, BlockMetadata

__all__ = ["DeltaDatasource", "IcebergDatasource", "commit_delta_write"]


def _join(base: str, rel: str) -> str:
    return base.rstrip("/") + "/" + rel.lstrip("/")


def _list_dir(path: str) -> List[str]:
    """All files under `path` (non-recursive names not required: callers
    filter by basename), [] when the directory does not exist."""
    from ray_tpu._private import fileio

    try:
        return fileio.expand_paths([path])
    except FileNotFoundError:
        return []


def _read_bytes(path: str) -> bytes:
    from ray_tpu._private import fileio

    with fileio.open_file(path, "rb") as f:
        return f.read()


# ---------------------------------------------------------------------------
# Delta Lake


_DELTA_COMMIT_RE = re.compile(r"^(\d{20})\.json$")
_DELTA_CKPT_RE = re.compile(r"^(\d{20})\.checkpoint(?:\.\d+\.\d+)?\.parquet$")

# Spark schemaString type name -> converter for partition-value strings
_PARTITION_CASTS = {
    "string": str, "integer": int, "long": int, "short": int, "byte": int,
    "double": float, "float": float, "boolean": lambda s: s == "true",
}


def _delta_log_files(table: str) -> Dict[str, List]:
    log_dir = _join(table, "_delta_log")
    commits: List[tuple] = []
    ckpts: Dict[int, List[str]] = {}
    for p in _list_dir(log_dir):
        base = p.rstrip("/").rsplit("/", 1)[-1]
        m = _DELTA_COMMIT_RE.match(base)
        if m:
            commits.append((int(m.group(1)), p))
            continue
        m = _DELTA_CKPT_RE.match(base)
        if m:
            ckpts.setdefault(int(m.group(1)), []).append(p)
    commits.sort()
    return {"commits": commits, "checkpoints": ckpts}


def _maplike_to_dict(v: Any) -> Dict[str, Any]:
    """partitionValues arrives as a dict (JSON commits) or a list of
    (key, value) pairs (pyarrow map type in checkpoint parquets)."""
    if v is None:
        return {}
    if isinstance(v, dict):
        return dict(v)
    return {k: val for k, val in v}


def _apply_action(state: Dict[str, Any], action: Dict[str, Any]) -> None:
    if "add" in action and action["add"] is not None:
        add = dict(action["add"])
        add["partitionValues"] = _maplike_to_dict(add.get("partitionValues"))
        if add.get("deletionVector"):
            raise NotImplementedError(
                "Delta deletion vectors are not supported; rewrite the "
                "table with `OPTIMIZE`/full rewrite to purge them")
        state["files"][add["path"]] = add
    if "remove" in action and action["remove"] is not None:
        state["files"].pop(action["remove"]["path"], None)
    if "metaData" in action and action["metaData"] is not None:
        state["metaData"] = action["metaData"]
    if "protocol" in action and action["protocol"] is not None:
        state["protocol"] = action["protocol"]


def _delta_snapshot(table: str, version: Optional[int]) -> Dict[str, Any]:
    log = _delta_log_files(table)
    commits, ckpts = log["commits"], log["checkpoints"]
    if not commits and not ckpts:
        raise FileNotFoundError(
            f"{table!r} is not a Delta table (no _delta_log commits)")
    max_version = max([v for v, _ in commits] + list(ckpts))
    target = max_version if version is None else int(version)
    if target > max_version:
        raise ValueError(f"version {target} > latest table version "
                         f"{max_version}")
    state: Dict[str, Any] = {"files": {}, "metaData": None, "protocol": None}
    # newest checkpoint at or below the target version seeds the replay
    usable = [v for v in ckpts if v <= target]
    start = -1
    if usable:
        import pyarrow.parquet as pq
        from ray_tpu._private import fileio

        start = max(usable)
        for part in sorted(ckpts[start]):
            with fileio.open_file(part, "rb") as f:
                rows = pq.read_table(f).to_pylist()
            for row in rows:
                _apply_action(state, row)
    for v, path in commits:
        if start < v <= target:
            for line in _read_bytes(path).decode().splitlines():
                if line.strip():
                    _apply_action(state, json.loads(line))
    proto = state.get("protocol") or {}
    if (proto.get("minReaderVersion") or 1) > 3:
        raise NotImplementedError(
            f"Delta minReaderVersion {proto['minReaderVersion']} > 3")
    for feat in (proto.get("readerFeatures") or []):
        # only features whose semantics this reader actually honors may
        # pass: columnMapping would silently surface physical column
        # names, v2Checkpoint uses UUID checkpoint names + sidecars the
        # discovery regex can't see — both must fail loudly, not read
        # wrong data
        if feat not in ("timestampNtz", "vacuumProtocolCheck"):
            raise NotImplementedError(f"Delta reader feature {feat!r}")
    meta = state.get("metaData") or {}
    schema = json.loads(meta["schemaString"]) if meta.get("schemaString") \
        else {"fields": []}
    state["version"] = target
    state["partition_cols"] = list(meta.get("partitionColumns") or [])
    state["schema_fields"] = {f["name"]: f.get("type")
                              for f in schema.get("fields", [])}
    return state


def _cast_partition(value: Optional[str], sql_type: Any):
    if value is None:
        return None
    cast = _PARTITION_CASTS.get(sql_type) if isinstance(sql_type, str) \
        else None
    return cast(value) if cast else value


class DeltaDatasource(Datasource):
    """Snapshot reads of a Delta Lake table, with `version=` time travel.

    reference: python/ray/data/read_api.py read_delta_sharing_tables (the
    reference's Delta surface goes through the delta-sharing client; here
    the open table protocol is read directly so plain `s3://bucket/table`
    layouts work with no server).
    """

    def __init__(self, table_uri: str, *, version: Optional[int] = None,
                 columns: Optional[List[str]] = None):
        self._table = str(table_uri).rstrip("/")
        self._columns = columns
        self._snap = _delta_snapshot(self._table, version)

    @property
    def version(self) -> int:
        return self._snap["version"]

    def get_name(self) -> str:
        return "Delta"

    def plan_row_count(self) -> Optional[int]:
        total = 0
        for add in self._snap["files"].values():
            stats = add.get("stats")
            if not stats:
                return None
            n = json.loads(stats).get("numRecords")
            if n is None:
                return None
            total += n
        return total

    def estimate_inmemory_data_size(self) -> Optional[int]:
        sizes = [a.get("size") for a in self._snap["files"].values()]
        if any(s is None for s in sizes):
            return None
        return sum(sizes)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        files = sorted(self._snap["files"].values(), key=lambda a: a["path"])
        if not files:
            return []
        table, columns = self._table, self._columns
        part_types = {c: self._snap["schema_fields"].get(c)
                      for c in self._snap["partition_cols"]}
        n_tasks = max(1, min(parallelism, len(files)))
        groups = [files[i::n_tasks] for i in range(n_tasks)]

        def make(group):
            def read() -> List[Block]:
                import pyarrow as pa
                import pyarrow.parquet as pq
                from ray_tpu._private import fileio

                # push the projection into the parquet read; partition
                # columns never exist in the files, so they are grafted
                # afterwards from the log
                # (falls back to a full read when only partition columns
                # are requested: columns=[] would drop the row count)
                file_cols = ([c for c in columns if c not in part_types]
                             or None) if columns else None
                out = []
                for add in group:
                    rel = urllib.parse.unquote(add["path"])
                    path = rel if "://" in rel else _join(table, rel)
                    with fileio.open_file(path, "rb") as f:
                        t = pq.read_table(f, columns=file_cols)
                    # partition columns live only in the log: graft them on
                    for col, sql_type in part_types.items():
                        if col in t.column_names:
                            continue
                        val = _cast_partition(
                            add["partitionValues"].get(col), sql_type)
                        t = t.append_column(
                            col, pa.array([val] * len(t)))
                    if columns:
                        t = t.select(columns)
                    out.append(t)
                return out
            return read

        tasks = []
        for g in groups:
            rows = 0
            for add in g:
                stats = add.get("stats")
                rows += (json.loads(stats).get("numRecords") or 0) \
                    if stats else 0
            meta = BlockMetadata(
                num_rows=rows,
                size_bytes=sum(a.get("size") or 0 for a in g))
            tasks.append(ReadTask(make(g), meta))
        return tasks


# -- Delta write (part files are written by the normal distributed write
#    path; this commits them into the log atomically from the driver) ------

_SPARK_TYPES = {
    "int64": "long", "int32": "integer", "int16": "short", "int8": "byte",
    "double": "double", "float": "float", "string": "string",
    "large_string": "string", "bool": "boolean", "binary": "binary",
    "date32[day]": "date",
}


def _spark_schema_string(arrow_schema) -> str:
    fields = []
    for f in arrow_schema:
        t = _SPARK_TYPES.get(str(f.type))
        if t is None:
            t = "timestamp" if str(f.type).startswith("timestamp") \
                else "string"
        fields.append({"name": f.name, "type": t, "nullable": True,
                       "metadata": {}})
    return json.dumps({"type": "struct", "fields": fields})


def commit_delta_write(table: str, parts, *, mode: str = "append") -> int:
    """Commit already-written parquet part files as one Delta version.

    `parts` is a list of absolute paths/URIs under `table`, or of
    (path, num_rows) pairs — when row counts travel with the paths (as
    Dataset.write_delta sends them) only ONE part's footer is opened
    (for the schema) instead of every part's.  mode='append' adds them;
    mode='overwrite' also removes every file in the current snapshot.
    Creates the table (protocol + metaData actions) when no log exists.
    Returns the committed version.
    """
    import uuid

    import pyarrow.parquet as pq
    from ray_tpu._private import fileio

    table = str(table).rstrip("/")
    if mode not in ("append", "overwrite"):
        raise ValueError(f"mode must be append|overwrite, got {mode!r}")
    log = _delta_log_files(table)
    have_log = bool(log["commits"]) or bool(log["checkpoints"])
    prev = _delta_snapshot(table, None) if have_log else None
    version = (prev["version"] + 1) if prev is not None else 0
    now_ms = int(__import__("time").time() * 1000)

    actions: List[Dict[str, Any]] = []
    arrow_schema = None
    adds = []
    for part in parts:
        p, n_rows = part if isinstance(part, (tuple, list)) else (part, None)
        if n_rows is not None:
            n_rows = int(n_rows)  # arrow scalars are not JSON-encodable
        if n_rows is None or arrow_schema is None:
            with fileio.open_file(p, "rb") as f:
                pf = pq.ParquetFile(f)
                if n_rows is None:
                    n_rows = pf.metadata.num_rows
                if arrow_schema is None:
                    arrow_schema = pf.schema_arrow
        rel = p[len(table):].lstrip("/") if p.startswith(table) else p
        adds.append({"add": {
            "path": urllib.parse.quote(rel),
            "partitionValues": {}, "size": fileio.filesize(p) or 0,
            "modificationTime": now_ms, "dataChange": True,
            "stats": json.dumps({"numRecords": n_rows}),
        }})
    if prev is None and arrow_schema is None:
        raise ValueError(
            "cannot create a Delta table from an empty write (no part "
            "files carry a schema); write at least one row")
    if prev is None:
        actions.append({"protocol": {"minReaderVersion": 1,
                                     "minWriterVersion": 2}})
        actions.append({"metaData": {
            "id": uuid.uuid4().hex, "format": {"provider": "parquet",
                                               "options": {}},
            "schemaString": _spark_schema_string(arrow_schema),
            "partitionColumns": [], "configuration": {},
            "createdTime": now_ms,
        }})
    elif mode == "overwrite":
        for path in prev["files"]:
            actions.append({"remove": {
                "path": path, "deletionTimestamp": now_ms,
                "dataChange": True}})
    actions.extend(adds)
    actions.append({"commitInfo": {"timestamp": now_ms,
                                   "operation": "WRITE",
                                   "operationParameters": {"mode": mode}}})
    log_dir = _join(table, "_delta_log")
    fileio.makedirs(log_dir)
    commit_path = _join(log_dir, f"{version:020d}.json")
    payload = "\n".join(json.dumps(a) for a in actions).encode()
    if not fileio.is_uri(commit_path):
        # O_EXCL create: a concurrent writer racing to the same version
        # loses with FileExistsError instead of silently overwriting
        try:
            with open(commit_path, "xb") as f:
                f.write(payload)
        except FileExistsError:
            raise RuntimeError(
                f"concurrent Delta commit at version {version}") from None
        return version
    # object stores: best-effort existence check (put-if-absent is not in
    # the fsspec surface; a true CAS needs the store's conditional put)
    if fileio.exists(commit_path):
        raise RuntimeError(f"concurrent Delta commit at version {version}")
    with fileio.open_file(commit_path, "wb") as f:
        f.write(payload)
    return version


# ---------------------------------------------------------------------------
# Apache Iceberg


_ICEBERG_META_RE = re.compile(r"^(?:v(\d+)|(\d+)-[0-9a-fA-F-]+)\.metadata\.json$")


def _strip_file_scheme(path: str) -> str:
    """file:///x, file://x and file:/x all mean local /x."""
    if path.startswith("file:"):
        path = path[5:]
        while path.startswith("//"):
            path = path[1:]
    return path


def _iceberg_latest_metadata(table: str) -> str:
    meta_dir = _join(table, "metadata")
    from ray_tpu._private import fileio

    hint = _join(meta_dir, "version-hint.text")
    if fileio.exists(hint):
        n = int(_read_bytes(hint).decode().strip())
        cand = _join(meta_dir, f"v{n}.metadata.json")
        if fileio.exists(cand):
            return cand
    best, best_seq = None, -1
    for p in _list_dir(meta_dir):
        base = p.rstrip("/").rsplit("/", 1)[-1]
        m = _ICEBERG_META_RE.match(base)
        if m:
            seq = int(m.group(1) or m.group(2))
            if seq > best_seq:
                best, best_seq = p, seq
    if best is None:
        raise FileNotFoundError(
            f"{table!r} is not an Iceberg table (no metadata/*.metadata.json)")
    return best


def _iceberg_arrow_type(iceberg_type):
    """Iceberg primitive type string -> arrow type, for typing the
    all-null back-fill of ADD-COLUMN evolution (blocks from pre- and
    post-evolution files must carry the same schema or concat fails).
    Unknown/nested types fall back to arrow's null type."""
    import pyarrow as pa

    t = iceberg_type if isinstance(iceberg_type, str) else None
    prim = {"boolean": pa.bool_(), "int": pa.int32(), "long": pa.int64(),
            "float": pa.float32(), "double": pa.float64(),
            "date": pa.date32(), "time": pa.time64("us"),
            "timestamp": pa.timestamp("us"),
            "timestamptz": pa.timestamp("us", tz="UTC"),
            "string": pa.string(), "uuid": pa.binary(16),
            "binary": pa.binary()}
    if t in prim:
        return prim[t]
    if t and t.startswith("decimal("):
        p, s = t[len("decimal("):-1].split(",")
        return pa.decimal128(int(p), int(s))
    if t and t.startswith("fixed("):
        return pa.binary(int(t[len("fixed("):-1]))
    return pa.null()


class IcebergDatasource(Datasource):
    """Snapshot reads of an Iceberg v1/v2 table (parquet or avro data
    files), with `snapshot_id=` time travel.

    reference: python/ray/data/read_api.py read_iceberg (delegates to
    pyiceberg; here the metadata.json -> manifest-list -> manifest chain
    is walked directly with the _avro.py codec).
    """

    def __init__(self, table_uri: str, *, snapshot_id: Optional[int] = None,
                 columns: Optional[List[str]] = None):
        self._table = str(table_uri).rstrip("/")
        self._columns = columns
        meta = json.loads(_read_bytes(_iceberg_latest_metadata(self._table)))
        self._location = _strip_file_scheme(
            (meta.get("location") or self._table).rstrip("/"))
        snap_id = snapshot_id if snapshot_id is not None \
            else meta.get("current-snapshot-id")
        snaps = {s["snapshot-id"]: s for s in meta.get("snapshots", [])}
        if snap_id is None or snap_id == -1 or not snaps:
            self._files: List[Dict[str, Any]] = []
            self._field_ids: Dict[str, int] = {}
            return
        if snap_id not in snaps:
            raise ValueError(f"snapshot {snap_id} not in table "
                             f"({sorted(snaps)})")
        self._field_ids = self._schema_field_ids(meta, snaps[snap_id])
        self._files = self._resolve_snapshot(snaps[snap_id])

    @staticmethod
    def _schema_field_ids(meta: Dict[str, Any],
                          snap: Dict[str, Any]) -> Dict[str, tuple]:
        """Column name -> (field-id, iceberg type) for the snapshot's
        schema.

        The Iceberg spec resolves columns by field-id, not name, so
        renames survive: the name a reader asks for is looked up in the
        TABLE schema, and the id is matched against each data file's
        parquet field_id metadata (get_read_tasks).  The type rides
        along so ADD-COLUMN back-fill nulls are typed consistently with
        blocks from post-evolution files."""
        schemas = meta.get("schemas") or []
        sid = snap.get("schema-id", meta.get("current-schema-id"))
        schema = next((s for s in schemas if s.get("schema-id") == sid),
                      None) or (schemas[-1] if schemas
                                else meta.get("schema") or {})
        out: Dict[str, tuple] = {}
        for f in schema.get("fields", []):
            if "id" in f and "name" in f:
                out[f["name"]] = (int(f["id"]), f.get("type"))
        return out

    def _remap(self, path: str) -> str:
        """Manifest paths are absolute URIs from the writer's vantage;
        remap them under the table URI the caller actually reached."""
        path = _strip_file_scheme(path)
        if path.startswith(self._location):
            return self._table + path[len(self._location):]
        loc_tail = self._location.split("://", 1)[-1]
        i = path.find(loc_tail)
        if i >= 0:
            return self._table + path[i + len(loc_tail):]
        return path

    def _resolve_snapshot(self, snap: Dict[str, Any]) -> List[Dict[str, Any]]:
        from . import _avro

        files: List[Dict[str, Any]] = []
        if snap.get("manifest-list"):
            manifests = _avro.read_container(
                _read_bytes(self._remap(snap["manifest-list"])))
        else:  # v1 tables may inline the manifest paths
            manifests = [{"manifest_path": p} for p in
                         snap.get("manifests", [])]
        for mf in manifests:
            if mf.get("content", 0) == 1:
                raise NotImplementedError(
                    "Iceberg delete manifests (merge-on-read) are not "
                    "supported; compact the table to copy-on-write")
            entries = _avro.read_container(
                _read_bytes(self._remap(mf["manifest_path"])))
            for e in entries:
                if e.get("status") == 2:     # DELETED
                    continue
                df = e["data_file"]
                if df.get("content", 0) != 0:
                    raise NotImplementedError(
                        "Iceberg delete files are not supported")
                files.append(df)
        return files

    def get_name(self) -> str:
        return "Iceberg"

    def plan_row_count(self) -> Optional[int]:
        counts = [f.get("record_count") for f in self._files]
        if any(c is None for c in counts):
            return None
        return sum(counts)

    def estimate_inmemory_data_size(self) -> Optional[int]:
        sizes = [f.get("file_size_in_bytes") for f in self._files]
        if any(s is None for s in sizes):
            return None
        return sum(sizes)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        if not self._files:
            return []
        files = sorted(self._files, key=lambda f: f["file_path"])
        columns = self._columns
        field_ids = self._field_ids
        remap = self._remap
        n_tasks = max(1, min(parallelism, len(files)))
        groups = [files[i::n_tasks] for i in range(n_tasks)]

        def resolve_parquet_columns(file_schema):
            """Requested name -> physical column name in THIS file via
            field-id (spec-correct under renames); falls back to the
            name itself when neither side carries an id.  A TABLE-schema
            column the file predates (ADD COLUMN evolution) resolves to
            None — projected as typed nulls, per the Iceberg spec; a
            name in neither the table schema nor the file is an error
            (typos must not come back as null columns)."""
            by_id: Dict[int, str] = {}
            for field in file_schema:
                fid = (field.metadata or {}).get(b"PARQUET:field_id")
                if fid is not None:
                    by_id[int(fid)] = field.name
            pairs = []
            for c in columns:
                fid, _ = field_ids.get(c, (None, None))
                if fid is not None and fid in by_id:
                    pairs.append((c, by_id[fid]))
                elif c in file_schema.names:
                    pairs.append((c, c))
                elif c in field_ids:
                    pairs.append((c, None))
                else:
                    raise KeyError(
                        f"column {c!r} is in neither the table schema "
                        f"nor the data file (schema columns: "
                        f"{sorted(field_ids)})")
            return pairs

        def make(group):
            paths = [(remap(f["file_path"]),
                      (f.get("file_format") or "PARQUET").upper())
                     for f in group]

            def read() -> List[Block]:
                import pyarrow as pa
                import pyarrow.parquet as pq
                from ray_tpu._private import fileio
                from . import _avro

                out = []
                for path, fmt in paths:
                    if fmt == "PARQUET":
                        with fileio.open_file(path, "rb") as f:
                            pf = pq.ParquetFile(f)
                            if columns is None:
                                t = pf.read()
                            elif not columns:
                                # zero-column projection keeps num_rows
                                # (count()-style reads); a pa.table({})
                                # rebuild would report 0 rows
                                t = pf.read(columns=[])
                            else:
                                pairs = resolve_parquet_columns(
                                    pf.schema_arrow)
                                nrows = pf.metadata.num_rows
                                t = pf.read(columns=[p for _, p in pairs
                                                     if p is not None])
                                t = pa.table(
                                    {c: (t.column(p) if p is not None
                                         else pa.nulls(
                                             nrows,
                                             _iceberg_arrow_type(
                                                 field_ids[c][1])))
                                     for c, p in pairs})
                    elif fmt == "AVRO":
                        rows = _avro.read_container(_read_bytes(path))
                        t = pa.Table.from_pylist(rows)
                        if columns is not None:
                            t = t.select(columns)
                    else:
                        raise NotImplementedError(
                            f"Iceberg data file format {fmt!r}")
                    out.append(t)
                return out
            return read

        tasks = []
        for g in groups:
            meta = BlockMetadata(
                num_rows=sum(f.get("record_count") or 0 for f in g),
                size_bytes=sum(f.get("file_size_in_bytes") or 0 for f in g))
            tasks.append(ReadTask(make(g), meta))
        return tasks
