"""Aggregation functions for Dataset.groupby / global aggregates.

Reference: python/ray/data/aggregate.py (AggregateFn, Count/Sum/Min/Max/
Mean/Std).  Implemented over arrow compute; each AggregateFn defines a
per-block partial and a cross-block combine, so aggregation runs as
distributed partials + a small driver-side reduce.
"""

from __future__ import annotations

import math
from typing import Any, Callable, List, Optional

import pyarrow as pa
import pyarrow.compute as pc


class AggregateFn:
    def __init__(self, name: str,
                 partial: Callable[[pa.Table], Any],
                 combine: Callable[[List[Any]], Any],
                 finalize: Optional[Callable[[Any], Any]] = None):
        self.name = name
        self.partial = partial
        self.combine = combine
        self.finalize = finalize or (lambda x: x)


def _scalar(v):
    try:
        return v.as_py()
    except AttributeError:
        return v


class Count(AggregateFn):
    def __init__(self, on: Optional[str] = None, alias_name=None):
        name = alias_name or ("count()" if on is None else f"count({on})")
        if on is None:
            partial = lambda t: t.num_rows  # noqa: E731
        else:
            partial = lambda t: t.num_rows - t.column(on).null_count  # noqa: E731
        super().__init__(name, partial, lambda parts: sum(parts))


class Sum(AggregateFn):
    def __init__(self, on: str, alias_name=None):
        super().__init__(
            alias_name or f"sum({on})",
            lambda t: _scalar(pc.sum(t.column(on))),
            lambda parts: sum(p for p in parts if p is not None))


class Min(AggregateFn):
    def __init__(self, on: str, alias_name=None):
        super().__init__(
            alias_name or f"min({on})",
            lambda t: _scalar(pc.min(t.column(on))),
            lambda parts: min(p for p in parts if p is not None))


class Max(AggregateFn):
    def __init__(self, on: str, alias_name=None):
        super().__init__(
            alias_name or f"max({on})",
            lambda t: _scalar(pc.max(t.column(on))),
            lambda parts: max(p for p in parts if p is not None))


class Mean(AggregateFn):
    def __init__(self, on: str, alias_name=None):
        def partial(t: pa.Table):
            col = t.column(on)
            n = t.num_rows - col.null_count
            s = _scalar(pc.sum(col)) or 0
            return (s, n)

        def combine(parts):
            s = sum(p[0] for p in parts)
            n = sum(p[1] for p in parts)
            return (s, n)

        super().__init__(alias_name or f"mean({on})", partial, combine,
                         lambda sn: (sn[0] / sn[1]) if sn[1] else None)


class Std(AggregateFn):
    """Parallel variance via per-block (n, mean, M2) + Chan combine."""

    def __init__(self, on: str, ddof: int = 1, alias_name=None):
        def partial(t: pa.Table):
            import numpy as np

            col = t.column(on)
            if col.null_count:
                col = pc.drop_null(col)
            vals = col.to_numpy(zero_copy_only=False)
            n = len(vals)
            if n == 0:
                return (0, 0.0, 0.0)
            m = float(np.mean(vals))
            m2 = float(np.sum((vals - m) ** 2))
            return (n, m, m2)

        def combine(parts):
            n, mean, m2 = 0, 0.0, 0.0
            for (nb, mb, m2b) in parts:
                if nb == 0:
                    continue
                delta = mb - mean
                tot = n + nb
                m2 = m2 + m2b + delta * delta * n * nb / tot
                mean = mean + delta * nb / tot
                n = tot
            return (n, mean, m2)

        def finalize(nm):
            n, _, m2 = nm
            if n - ddof <= 0:
                return None
            return math.sqrt(m2 / (n - ddof))

        super().__init__(alias_name or f"std({on})", partial, combine,
                         finalize)
