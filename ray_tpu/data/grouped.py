"""GroupedData: the result of Dataset.groupby.

Reference: python/ray/data/grouped_data.py — aggregate / count / sum /
min / max / mean / std / map_groups, executed as a hash-partition exchange
followed by per-partition grouped reduction (execution.py AllToAllOperator
kind='groupby').
"""

from __future__ import annotations

from typing import Callable, Optional

from . import aggregate as agg_mod
from . import logical as L
from .dataset import Dataset


class GroupedData:
    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: agg_mod.AggregateFn) -> Dataset:
        return Dataset(L.GroupByAggregate(self._ds._dag, self._key,
                                          list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(agg_mod.Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Mean(on))

    def std(self, on: str, ddof: int = 1) -> Dataset:
        return self.aggregate(agg_mod.Std(on, ddof))

    def map_groups(self, fn: Callable, *, batch_format: str = "numpy"
                   ) -> Dataset:
        return Dataset(L.MapGroups(self._ds._dag, self._key, fn,
                                   batch_format=batch_format))
