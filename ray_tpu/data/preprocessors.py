"""Preprocessors: fit/transform feature pipelines over Datasets.

Analog of the reference's ray.data.preprocessors (reference:
python/ray/data/preprocessors/ — scaler.py StandardScaler/MinMaxScaler,
encoder.py OneHotEncoder/LabelEncoder/OrdinalEncoder, imputer.py
SimpleImputer, concatenator.py, batch_mapper.py, chain.py): statistics are
computed with the Dataset's distributed aggregates, transforms run as
map_batches over numpy columns — and compose with iter_jax_batches to feed
device-resident training batches.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .dataset import Dataset


class Preprocessor:
    """Base: subclasses implement _fit(ds) -> stats dict and
    _transform_numpy(batch) using self.stats_."""

    _is_fittable = True

    def __init__(self):
        self.stats_: Optional[Dict[str, Any]] = None

    def fit(self, ds: Dataset) -> "Preprocessor":
        if self._is_fittable:
            self.stats_ = self._fit(ds)
        return self

    def fit_transform(self, ds: Dataset) -> Dataset:
        return self.fit(ds).transform(ds)

    def transform(self, ds: Dataset) -> Dataset:
        if self._is_fittable and self.stats_ is None:
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return ds.map_batches(self._transform_numpy, batch_format="numpy")

    def transform_batch(self, batch: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
        if self._is_fittable and self.stats_ is None:
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return self._transform_numpy(dict(batch))

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        raise NotImplementedError

    def _transform_numpy(self, batch: Dict[str, np.ndarray]
                         ) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference: preprocessors/scaler.py)."""

    def __init__(self, columns: List[str], ddof: int = 0):
        super().__init__()
        self.columns = list(columns)
        self.ddof = ddof

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        from .aggregate import Mean, Std

        # one combined aggregate pass over all columns, not 2k executions
        aggs = [Mean(c) for c in self.columns] + \
            [Std(c, ddof=self.ddof) for c in self.columns]
        stats = dict(ds.aggregate(*aggs))
        for c in self.columns:
            s = stats.get(f"std({c})")
            if not s or s <= 0:
                stats[f"std({c})"] = 1.0
        return stats

    def _transform_numpy(self, batch):
        for c in self.columns:
            mu = self.stats_[f"mean({c})"]
            sd = self.stats_[f"std({c})"]
            batch[c] = (np.asarray(batch[c], np.float64) - mu) / sd
        return batch


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        from .aggregate import Max, Min

        return dict(ds.aggregate(*[Min(c) for c in self.columns],
                                 *[Max(c) for c in self.columns]))

    def _transform_numpy(self, batch):
        for c in self.columns:
            lo = self.stats_[f"min({c})"]
            hi = self.stats_[f"max({c})"]
            span = (hi - lo) or 1.0
            batch[c] = (np.asarray(batch[c], np.float64) - lo) / span
        return batch


class MaxAbsScaler(Preprocessor):
    """x / max(|x|) per column."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        from .aggregate import Max, Min

        raw = ds.aggregate(*[Min(c) for c in self.columns],
                           *[Max(c) for c in self.columns])
        return {f"abs_max({c})": max(abs(raw[f"min({c})"]),
                                     abs(raw[f"max({c})"])) or 1.0
                for c in self.columns}

    def _transform_numpy(self, batch):
        for c in self.columns:
            batch[c] = (np.asarray(batch[c], np.float64)
                        / self.stats_[f"abs_max({c})"])
        return batch


class LabelEncoder(Preprocessor):
    """Category -> dense int id for one label column."""

    def __init__(self, label_column: str):
        super().__init__()
        self.label_column = label_column

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        vals = sorted(ds.unique(self.label_column), key=str)
        return {"classes": {v: i for i, v in enumerate(vals)}}

    def _transform_numpy(self, batch):
        m = self.stats_["classes"]
        col = batch[self.label_column]
        batch[self.label_column] = np.asarray(
            [m[v] for v in np.asarray(col).tolist()], np.int64)
        return batch

    def inverse_transform_batch(self, batch):
        inv = {i: v for v, i in self.stats_["classes"].items()}
        col = batch[self.label_column]
        batch[self.label_column] = np.asarray(
            [inv[int(v)] for v in np.asarray(col).tolist()])
        return batch


class OrdinalEncoder(Preprocessor):
    """Categories -> dense int ids for several columns."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        return {c: {v: i for i, v in enumerate(
            sorted(ds.unique(c), key=str))} for c in self.columns}

    def _transform_numpy(self, batch):
        for c in self.columns:
            m = self.stats_[c]
            batch[c] = np.asarray(
                [m[v] for v in np.asarray(batch[c]).tolist()], np.int64)
        return batch


class OneHotEncoder(Preprocessor):
    """Category columns -> {col}_{value} indicator columns."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        return {c: sorted(ds.unique(c), key=str) for c in self.columns}

    def _transform_numpy(self, batch):
        for c in self.columns:
            col = np.asarray(batch.pop(c))
            for v in self.stats_[c]:
                batch[f"{c}_{v}"] = (col == v).astype(np.int64)
        return batch


class SimpleImputer(Preprocessor):
    """Fill NaNs with mean / most_frequent / constant."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Any = None):
        super().__init__()
        if strategy not in ("mean", "most_frequent", "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError("strategy='constant' requires fill_value")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value

    def _fit(self, ds: Dataset) -> Dict[str, Any]:
        if self.strategy == "constant":
            return {c: self.fill_value for c in self.columns}
        if self.strategy == "mean":
            # nan-skipping mean (Dataset.mean propagates NaN)
            out = {}
            for c in self.columns:
                total, n = 0.0, 0
                for row in ds.select_columns([c]).iter_rows():
                    v = row[c]
                    if v is not None and v == v:
                        total += float(v)
                        n += 1
                out[c] = total / n if n else 0.0
            return out
        out = {}
        for c in self.columns:
            counts: Dict[Any, int] = {}
            for row in ds.select_columns([c]).iter_rows():
                v = row[c]
                if v is not None and v == v:  # skip None/NaN
                    counts[v] = counts.get(v, 0) + 1
            out[c] = max(counts.items(), key=lambda kv: kv[1])[0] \
                if counts else 0
        return out

    def _transform_numpy(self, batch):
        for c in self.columns:
            col = np.asarray(batch[c], dtype=object if
                             self.strategy == "most_frequent" else None)
            fill = self.stats_[c]
            if col.dtype == object:
                col = np.asarray([fill if v is None or v != v else v
                                  for v in col.tolist()])
            else:
                col = np.where(np.isnan(col.astype(np.float64)), fill, col)
            batch[c] = col
        return batch


class Concatenator(Preprocessor):
    """Merge numeric columns into one float vector column."""

    _is_fittable = False

    def __init__(self, columns: List[str], output_column_name: str = "concat",
                 dtype=np.float32):
        super().__init__()
        self.columns = list(columns)
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _transform_numpy(self, batch):
        parts = []
        for c in self.columns:
            col = np.asarray(batch.pop(c), self.dtype)
            parts.append(col[:, None] if col.ndim == 1 else col)
        batch[self.output_column_name] = np.concatenate(parts, axis=1)
        return batch


class BatchMapper(Preprocessor):
    """Arbitrary stateless batch UDF as a preprocessor."""

    _is_fittable = False

    def __init__(self, fn: Callable[[Dict[str, np.ndarray]],
                                    Dict[str, np.ndarray]]):
        super().__init__()
        self.fn = fn

    def _transform_numpy(self, batch):
        return self.fn(batch)


class Chain(Preprocessor):
    """Sequentially fit+apply preprocessors (reference: chain.py)."""

    def __init__(self, *preprocessors: Preprocessor):
        super().__init__()
        self.preprocessors = list(preprocessors)

    def fit(self, ds: Dataset) -> "Chain":
        for p in self.preprocessors:
            ds = p.fit_transform(ds)
        self.stats_ = {"fitted": True}
        return self

    def transform(self, ds: Dataset) -> Dataset:
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def fit_transform(self, ds: Dataset) -> Dataset:
        self.fit(ds)
        return self.transform(ds)

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch
