"""Block model: the unit of distributed data.

The canonical block is a ``pyarrow.Table`` (the reference supports Arrow and
pandas blocks — reference: python/ray/data/block.py, BlockAccessor).  A
``BlockAccessor`` unifies operations over whatever a user function returned
(arrow table, pandas DataFrame, dict-of-numpy batch, or list of rows).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa

Block = pa.Table  # canonical on-wire block type

# Name used for single-column datasets built from raw items/tensors
# (the reference uses the same name, python/ray/data/block.py).
VALUE_COL = "item"


@dataclass
class BlockMetadata:
    """Small, driver-resident description of a block (reference:
    python/ray/data/block.py BlockMetadata)."""

    num_rows: int
    size_bytes: int
    schema: Optional[pa.Schema] = None
    input_files: List[str] = field(default_factory=list)
    exec_stats: Optional[Dict[str, float]] = None


def _is_tensor_like(value: Any) -> bool:
    return isinstance(value, np.ndarray) and value.ndim > 1


class _ArrowTensorMarker:
    """Marks a >1-D numpy column stored row-wise as fixed-shape lists."""


def _np_to_arrow_array(arr: np.ndarray) -> pa.Array:
    if arr.ndim == 1:
        if arr.dtype.kind in "US":
            return pa.array(arr.tolist())
        return pa.array(arr)
    # fixed-shape tensor column: store as FixedShapeTensorType when
    # available so round-trips preserve shape
    try:
        tensor_type = pa.fixed_shape_tensor(pa.from_numpy_dtype(arr.dtype),
                                            arr.shape[1:])
        storage = pa.FixedSizeListArray.from_arrays(
            pa.array(arr.reshape(arr.shape[0], -1).ravel()),
            int(np.prod(arr.shape[1:])))
        return pa.ExtensionArray.from_storage(tensor_type, storage)
    except Exception:
        return pa.array(list(arr))


def _arrow_col_to_np(col: pa.ChunkedArray) -> np.ndarray:
    typ = col.type
    if isinstance(typ, pa.FixedShapeTensorType):
        combined = col.combine_chunks()
        if isinstance(combined, pa.ChunkedArray):
            combined = combined.chunk(0) if combined.num_chunks else \
                pa.array([], typ)
        flat = combined.storage.flatten().to_numpy(zero_copy_only=False)
        shape = (len(col),) + tuple(typ.shape)
        return flat.reshape(shape)
    return col.to_numpy(zero_copy_only=False)


def batch_to_block(batch: Any) -> Block:
    """Convert a user-returned batch to the canonical arrow block."""
    if isinstance(batch, pa.Table):
        return batch
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:
        pass
    if isinstance(batch, dict):
        cols, names = [], []
        for k, v in batch.items():
            names.append(k)
            if isinstance(v, np.ndarray):
                cols.append(_np_to_arrow_array(v))
            else:
                cols.append(pa.array(list(v)))
        return pa.Table.from_arrays(cols, names=names)
    if isinstance(batch, np.ndarray):
        return pa.Table.from_arrays([_np_to_arrow_array(batch)],
                                    names=[VALUE_COL])
    if isinstance(batch, list):
        return rows_to_block(batch)
    raise TypeError(
        f"cannot convert batch of type {type(batch).__name__} to a block; "
        f"return pyarrow.Table, pandas.DataFrame, dict of numpy arrays, or "
        f"a list of rows")


def rows_to_block(rows: Sequence[Any]) -> Block:
    """Build a block from python rows (dicts become columns; anything else
    goes into the single `item` column)."""
    if rows and all(isinstance(r, dict) for r in rows):
        names: List[str] = []
        for r in rows:
            for k in r:
                if k not in names:
                    names.append(k)
        cols = []
        for name in names:
            vals = [r.get(name) for r in rows]
            if vals and all(_is_tensor_like(v) or isinstance(v, np.ndarray)
                            for v in vals):
                try:
                    stacked = np.stack(vals)
                    cols.append(_np_to_arrow_array(stacked))
                    continue
                except Exception:
                    pass
            cols.append(pa.array(vals))
        return pa.Table.from_arrays(cols, names=names)
    vals = list(rows)
    if vals and all(isinstance(v, np.ndarray) for v in vals):
        try:
            return pa.Table.from_arrays(
                [_np_to_arrow_array(np.stack(vals))], names=[VALUE_COL])
        except Exception:
            pass
    return pa.Table.from_arrays([pa.array(vals)], names=[VALUE_COL])


class BlockAccessor:
    """Operations over a canonical arrow block (reference:
    python/ray/data/_internal/arrow_block.py ArrowBlockAccessor)."""

    def __init__(self, block: Block):
        if not isinstance(block, pa.Table):
            block = batch_to_block(block)
        self._table = block

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(block)

    def to_arrow(self) -> pa.Table:
        return self._table

    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> pa.Schema:
        return self._table.schema

    def get_metadata(self, input_files: Optional[List[str]] = None,
                     exec_stats: Optional[Dict[str, float]] = None
                     ) -> BlockMetadata:
        return BlockMetadata(num_rows=self.num_rows(),
                             size_bytes=self.size_bytes(),
                             schema=self.schema(),
                             input_files=input_files or [],
                             exec_stats=exec_stats)

    def to_pandas(self):
        return self._table.to_pandas()

    def to_numpy(self, columns: Optional[List[str]] = None
                 ) -> Dict[str, np.ndarray]:
        cols = columns or self._table.column_names
        return {c: _arrow_col_to_np(self._table.column(c)) for c in cols}

    def to_batch(self, batch_format: str):
        if batch_format in ("pyarrow", "arrow"):
            return self._table
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("numpy", "default", None):
            return self.to_numpy()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for batch in self._table.to_batches():
            cols = {name: _arrow_col_to_np(pa.chunked_array([batch.column(i)]))
                    for i, name in enumerate(batch.schema.names)}
            for i in range(batch.num_rows):
                yield {name: col[i] for name, col in cols.items()}

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def take(self, indices: Sequence[int]) -> Block:
        return self._table.take(pa.array(indices, type=pa.int64()))

    def select(self, columns: List[str]) -> Block:
        return self._table.select(columns)

    def drop(self, columns: List[str]) -> Block:
        keep = [c for c in self._table.column_names if c not in columns]
        return self._table.select(keep)

    def rename(self, mapping: Dict[str, str]) -> Block:
        names = [mapping.get(c, c) for c in self._table.column_names]
        return self._table.rename_columns(names)

    def random_permutation(self, seed: Optional[int]) -> Block:
        rng = np.random.RandomState(seed)
        idx = rng.permutation(self.num_rows())
        return self.take(idx.tolist())

    def sort(self, key, descending: bool = False) -> Block:
        order = "descending" if descending else "ascending"
        if isinstance(key, str):
            key = [key]
        return self._table.sort_by([(k, order) for k in key])

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if b is not None and b.num_rows >= 0]
        if not blocks:
            return pa.table({})
        nonempty = [b for b in blocks if b.num_rows > 0]
        if not nonempty:
            return blocks[0]
        return pa.concat_tables(nonempty, promote_options="default")


class BlockBuilder:
    """Accumulates rows/batches into bounded-size output blocks (reference:
    python/ray/data/_internal/delegating_block_builder.py)."""

    def __init__(self, target_max_bytes: Optional[int] = None):
        self._rows: List[Any] = []
        self._blocks: List[Block] = []
        self._target = target_max_bytes

    def add_row(self, row: Any) -> None:
        self._rows.append(row)

    def add_block(self, block: Any) -> None:
        self._flush_rows()
        self._blocks.append(batch_to_block(block))

    def _flush_rows(self) -> None:
        if self._rows:
            self._blocks.append(rows_to_block(self._rows))
            self._rows = []

    def build(self) -> Block:
        self._flush_rows()
        return BlockAccessor.concat(self._blocks)
