"""Logical plan: lazy operator DAG built by Dataset transforms.

Reference: python/ray/data/_internal/logical/ — logical operators +
LogicalPlan; the optimizer (planner.py here) fuses map chains before
physical planning, mirroring the reference's OperatorFusionRule.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


class LogicalOp:
    """A node in the logical DAG; inputs are upstream LogicalOps."""

    name = "Op"

    def __init__(self, inputs: List["LogicalOp"]):
        self.inputs = inputs

    def __repr__(self):
        return self.name


class Read(LogicalOp):
    name = "Read"

    def __init__(self, datasource, parallelism: int = -1):
        super().__init__([])
        self.datasource = datasource
        self.parallelism = parallelism
        self.name = f"Read{datasource.get_name()}"


class InputData(LogicalOp):
    """Already-executed bundles (materialized datasets)."""

    name = "InputData"

    def __init__(self, bundles):
        super().__init__([])
        self.bundles = bundles


# --- row/batch transforms (fusable) ---------------------------------------

class AbstractMap(LogicalOp):
    """Common base for per-block transforms.  ``fn_kind`` distinguishes how
    the user fn consumes data: 'batch', 'row', 'flat', 'filter', 'block'."""

    def __init__(self, input_op: LogicalOp, fn: Callable, fn_kind: str, *,
                 batch_size: Optional[int] = None,
                 batch_format: Optional[str] = None,
                 fn_args: Tuple = (), fn_kwargs: Optional[Dict] = None,
                 compute: Optional[Any] = None,
                 resources: Optional[Dict[str, float]] = None,
                 name: Optional[str] = None):
        super().__init__([input_op])
        self.fn = fn
        self.fn_kind = fn_kind
        self.batch_size = batch_size
        self.batch_format = batch_format
        self.fn_args = fn_args
        self.fn_kwargs = fn_kwargs or {}
        self.compute = compute
        self.resources = resources or {}
        self.name = name or f"Map({getattr(fn, '__name__', 'fn')})"


class MapBatches(AbstractMap):
    def __init__(self, input_op, fn, **kw):
        kw.setdefault("name", f"MapBatches({getattr(fn, '__name__', 'fn')})")
        super().__init__(input_op, fn, "batch", **kw)


class MapRows(AbstractMap):
    def __init__(self, input_op, fn, **kw):
        kw.setdefault("name", f"Map({getattr(fn, '__name__', 'fn')})")
        super().__init__(input_op, fn, "row", **kw)


class Filter(AbstractMap):
    def __init__(self, input_op, fn, **kw):
        kw.setdefault("name", f"Filter({getattr(fn, '__name__', 'fn')})")
        super().__init__(input_op, fn, "filter", **kw)


class FlatMap(AbstractMap):
    def __init__(self, input_op, fn, **kw):
        kw.setdefault("name", f"FlatMap({getattr(fn, '__name__', 'fn')})")
        super().__init__(input_op, fn, "flat", **kw)


class MapBlocks(AbstractMap):
    """Internal: fn(block)->block transform (writes, projections)."""

    def __init__(self, input_op, fn, **kw):
        kw.setdefault("name", f"MapBlocks({getattr(fn, '__name__', 'fn')})")
        super().__init__(input_op, fn, "block", **kw)


# --- all-to-all ops --------------------------------------------------------

class AbstractAllToAll(LogicalOp):
    def __init__(self, input_op: LogicalOp, num_outputs: Optional[int]):
        super().__init__([input_op])
        self.num_outputs = num_outputs


class Repartition(AbstractAllToAll):
    name = "Repartition"

    def __init__(self, input_op, num_blocks: int, shuffle: bool = False):
        super().__init__(input_op, num_blocks)
        self.shuffle = shuffle


class RandomShuffle(AbstractAllToAll):
    name = "RandomShuffle"

    def __init__(self, input_op, seed: Optional[int] = None,
                 num_outputs: Optional[int] = None):
        super().__init__(input_op, num_outputs)
        self.seed = seed


class Sort(AbstractAllToAll):
    name = "Sort"

    def __init__(self, input_op, key, descending: bool = False,
                 num_outputs: Optional[int] = None):
        super().__init__(input_op, num_outputs)
        self.key = key
        self.descending = descending


class GroupByAggregate(AbstractAllToAll):
    name = "Aggregate"

    def __init__(self, input_op, key: Optional[str], aggs: List,
                 num_outputs: Optional[int] = None):
        super().__init__(input_op, num_outputs)
        self.key = key
        self.aggs = aggs


class MapGroups(AbstractAllToAll):
    name = "MapGroups"

    def __init__(self, input_op, key: Optional[str], fn: Callable,
                 batch_format: Optional[str] = None,
                 num_outputs: Optional[int] = None):
        super().__init__(input_op, num_outputs)
        self.key = key
        self.fn = fn
        self.batch_format = batch_format


# --- n-ary / misc ----------------------------------------------------------

class Limit(LogicalOp):
    name = "Limit"

    def __init__(self, input_op, limit: int):
        super().__init__([input_op])
        self.limit = limit


class Union(LogicalOp):
    name = "Union"

    def __init__(self, inputs: List[LogicalOp]):
        super().__init__(inputs)


class Zip(LogicalOp):
    name = "Zip"

    def __init__(self, left: LogicalOp, right: LogicalOp):
        super().__init__([left, right])


@dataclass
class LogicalPlan:
    dag: LogicalOp

    def sources(self) -> List[LogicalOp]:
        out, seen, stack = [], set(), [self.dag]
        while stack:
            op = stack.pop()
            if id(op) in seen:
                continue
            seen.add(id(op))
            if not op.inputs:
                out.append(op)
            stack.extend(op.inputs)
        return out
