"""Streaming execution of logical plans over ray_tpu tasks.

Reference: python/ray/data/_internal/execution/ — StreamingExecutor
(streaming_executor.py:48) drives a DAG of PhysicalOperators; MapOperator
(operators/map_operator.py:44) fans block transforms out as tasks with
bounded in-flight budgets and backpressure; all-to-all ops (shuffle/sort/
groupby) run partition+reduce phases.

TPU-first notes: blocks are host-side arrow tables moved via the object
plane; device placement happens only at iteration time (iterator.py) where
batches are staged into HBM with double buffering.  The executor itself is
a pure control loop — no data flows through the driver except metadata.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu

from . import logical as L
from .block import Block, BlockAccessor, BlockBuilder, BlockMetadata, \
    batch_to_block, rows_to_block
from .context import DataContext


@dataclass
class RefBundle:
    """A block reference + its metadata (reference:
    _internal/execution/interfaces/ref_bundle.py).

    `order` is the bundle's logical position (lexicographic): assigned
    at sources, carried 1:1 through maps, re-based by Union/AllToAll.
    Tasks complete out of order under load, so any operator whose
    semantics depend on row order (Zip) must sort by it — buffering in
    arrival order silently mispairs rows."""

    block_ref: Any  # ObjectRef[Block]
    metadata: BlockMetadata
    order: Tuple[int, ...] = ()


# ---------------------------------------------------------------------------
# Remote task bodies (run on workers)

def _apply_stage(blocks: List[Block], stage: Dict) -> List[Block]:
    kind = stage["kind"]
    fn = stage["fn"]
    if stage.get("is_class") and isinstance(fn, type):
        # task-pool path never takes class UDFs (dataset.py validates),
        # but a directly-built plan could: instantiate per call
        fn = fn(*(stage.get("fn_constructor_args") or ()),
                **(stage.get("fn_constructor_kwargs") or {}))
    fn_args = stage.get("fn_args") or ()
    fn_kwargs = stage.get("fn_kwargs") or {}
    if kind == "block":
        return [batch_to_block(fn(b, *fn_args, **fn_kwargs)) for b in blocks]
    if kind == "batch":
        batch_size = stage.get("batch_size")
        batch_format = stage.get("batch_format") or "numpy"
        out = []
        for b in blocks:
            acc = BlockAccessor(b)
            n = acc.num_rows()
            if batch_size is None or batch_size >= n:
                slices = [b] if n else []
            else:
                slices = [acc.slice(i, min(i + batch_size, n))
                          for i in range(0, n, batch_size)]
            builder = BlockBuilder()
            for s in slices:
                res = fn(BlockAccessor(s).to_batch(batch_format),
                         *fn_args, **fn_kwargs)
                if hasattr(res, "__next__") or (
                        hasattr(res, "__iter__")
                        and not isinstance(res, (dict, list, tuple))
                        and type(res).__module__.split(".")[0]
                        not in ("numpy", "pandas", "pyarrow")):
                    for r in res:
                        builder.add_block(batch_to_block(r))
                else:
                    builder.add_block(batch_to_block(res))
            out.append(builder.build())
        return out
    # row-wise kinds
    out = []
    for b in blocks:
        builder = BlockBuilder()
        for row in BlockAccessor(b).iter_rows():
            if kind == "row":
                builder.add_row(fn(row, *fn_args, **fn_kwargs))
            elif kind == "filter":
                if fn(row, *fn_args, **fn_kwargs):
                    builder.add_row(row)
            elif kind == "flat":
                for r in fn(row, *fn_args, **fn_kwargs):
                    builder.add_row(r)
            else:
                raise ValueError(f"unknown stage kind {kind}")
        out.append(builder.build())
    return out


def _map_task(chain: List[Dict], *blocks: Block):
    """Apply a fused chain of stages to input block(s); returns
    (block, metadata)."""
    t0 = time.perf_counter()
    out = _apply_stage(list(blocks), chain[0])
    for stage in chain[1:]:
        out = _apply_stage(out, stage)
    block = BlockAccessor.concat(out)
    meta = BlockAccessor(block).get_metadata(
        exec_stats={"wall_s": time.perf_counter() - t0})
    return block, meta


def _read_task(rt, chain: List[Dict]):
    """Run a ReadTask then any fused downstream stages."""
    t0 = time.perf_counter()
    blocks = list(rt())
    for stage in chain:
        blocks = _apply_stage(blocks, stage)
    block = BlockAccessor.concat(blocks)
    meta = BlockAccessor(block).get_metadata(
        input_files=rt.metadata.input_files,
        exec_stats={"wall_s": time.perf_counter() - t0})
    return block, meta


def _read_task_streaming(rt, chain: List[Dict]):
    """Streaming variant of _read_task: each source block flows through
    the fused stages and out of the task as soon as it is produced —
    the task never holds the whole output (reference: the streaming
    executor's generator-based block returns).  Yields block, meta,
    block, meta, ..."""
    for b in rt():
        t0 = time.perf_counter()
        out = [b]
        for stage in chain:
            out = _apply_stage(out, stage)
        for ob in out:
            meta = BlockAccessor(ob).get_metadata(
                input_files=rt.metadata.input_files,
                exec_stats={"wall_s": time.perf_counter() - t0})
            yield ob
            yield meta
            t0 = time.perf_counter()


def _map_task_streaming(chain: List[Dict], *blocks: Block):
    """Streaming variant of _map_task: yields each output block (and its
    metadata) without concatenating the task's whole output."""
    t0 = time.perf_counter()
    out = _apply_stage(list(blocks), chain[0])
    for stage in chain[1:]:
        out = _apply_stage(out, stage)
    for ob in out:
        meta = BlockAccessor(ob).get_metadata(
            exec_stats={"wall_s": time.perf_counter() - t0})
        yield ob
        yield meta
        t0 = time.perf_counter()


def _slice_task(n: int, block: Block):
    acc = BlockAccessor(block)
    out = acc.slice(0, min(n, acc.num_rows()))
    return out, BlockAccessor(out).get_metadata()


def _partition_task(spec: Dict, block: Block):
    """Split one block into spec['n'] parts (hash/random/range)."""
    acc = BlockAccessor(block)
    n = spec["n"]
    how = spec["how"]
    nrows = acc.num_rows()
    if nrows == 0:
        empty = acc.slice(0, 0)
        return tuple(empty for _ in range(n)) if n > 1 else empty
    if how == "random":
        rng = np.random.RandomState(spec.get("seed"))
        assign = rng.randint(0, n, size=nrows)
    elif how == "round_robin":
        assign = np.arange(nrows) % n
    elif how == "contig":
        # contiguous global ranges: row with global index g goes to the
        # output whose [cuts[j], cuts[j+1]) contains g — repartition
        # preserves row order (reference: shuffle=False repartition)
        start = spec["start"]
        cuts = np.asarray(spec["cuts"])  # n+1 absolute boundaries
        assign = np.searchsorted(cuts, start + np.arange(nrows),
                                 side="right") - 1
        assign = np.clip(assign, 0, n - 1)
    elif how == "hash":
        key = spec["key"]
        col = acc.to_numpy([key])[key]
        # stable hash of the key column
        import pandas as pd

        assign = pd.util.hash_array(np.asarray(col)) % n
    elif how == "range":
        key = spec["key"]
        boundaries = spec["boundaries"]
        col = np.asarray(acc.to_numpy([key])[key])
        assign = np.searchsorted(np.asarray(boundaries), col,
                                 side="right")
        if spec.get("descending"):
            assign = (n - 1) - assign
    else:
        raise ValueError(how)
    parts = []
    for i in range(n):
        idx = np.nonzero(assign == i)[0]
        parts.append(acc.take(idx.tolist()))
    return tuple(parts) if n > 1 else parts[0]


def _reduce_task(spec: Dict, *parts: Block):
    """Combine partition pieces into one output block."""
    block = BlockAccessor.concat([p for p in parts if p is not None])
    acc = BlockAccessor(block)
    how = spec["how"]
    if how == "shuffle":
        block = acc.random_permutation(spec.get("seed"))
    elif how == "sort":
        block = acc.sort(spec["key"], spec.get("descending", False))
    elif how == "aggregate":
        block = _aggregate_block(block, spec["key"], spec["aggs"])
    elif how == "map_groups":
        block = _map_groups_block(block, spec["key"], spec["fn"],
                                  spec.get("batch_format") or "numpy")
    elif how == "concat":
        pass
    else:
        raise ValueError(how)
    return block, BlockAccessor(block).get_metadata()


def _iter_groups(block: Block, key: str):
    acc = BlockAccessor(block)
    if acc.num_rows() == 0:
        return
    block = acc.sort(key)
    acc = BlockAccessor(block)
    keys = np.asarray(acc.to_numpy([key])[key])
    # group boundaries in the sorted key column
    change = np.nonzero(keys[1:] != keys[:-1])[0] + 1
    starts = np.concatenate([[0], change])
    ends = np.concatenate([change, [len(keys)]])
    for s, e in zip(starts, ends):
        yield keys[s], acc.slice(int(s), int(e))


def _aggregate_block(block: Block, key: Optional[str], aggs) -> Block:
    rows = []
    if key is None:
        row = {}
        for agg in aggs:
            row[agg.name] = agg.finalize(agg.combine([agg.partial(block)]))
        rows.append(row)
    else:
        for kval, group in _iter_groups(block, key):
            row = {key: kval}
            for agg in aggs:
                row[agg.name] = agg.finalize(
                    agg.combine([agg.partial(group)]))
            rows.append(row)
    return rows_to_block(rows)


def _map_groups_block(block: Block, key: Optional[str], fn,
                      batch_format: str) -> Block:
    builder = BlockBuilder()
    if key is None:
        res = fn(BlockAccessor(block).to_batch(batch_format))
        builder.add_block(batch_to_block(res))
    else:
        for _, group in _iter_groups(block, key):
            res = fn(BlockAccessor(group).to_batch(batch_format))
            builder.add_block(batch_to_block(res))
    return builder.build()


def _sample_task(key, k: int, block: Block):
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return []
    idx = np.linspace(0, n - 1, num=min(k, n), dtype=np.int64)
    sample = BlockAccessor(acc.take(idx.tolist()))
    col = sample.to_numpy([key] if isinstance(key, str) else key)
    return list(np.asarray(col[key if isinstance(key, str) else key[0]]))


def _zip_task(l_off: int, slices: List[Tuple[int, int, int]],
              left: Block, *rights: Block):
    """Zip: align `left` with slices of right-side blocks.
    slices: (right_block_index, start_in_right, length)."""
    import pyarrow as pa

    parts = []
    for (ri, start, length) in slices:
        parts.append(BlockAccessor(rights[ri]).slice(start, start + length))
    right = BlockAccessor.concat(parts)
    lt = BlockAccessor(left).to_arrow()
    rt = BlockAccessor(right).to_arrow()
    cols = {c: lt.column(c) for c in lt.column_names}
    for c in rt.column_names:
        name = c if c not in cols else f"{c}_1"
        cols[name] = rt.column(c)
    out = pa.table(cols)
    return out, BlockAccessor(out).get_metadata()


# ---------------------------------------------------------------------------
# Physical operators

@dataclass
class _TaskRec:
    refs: List[Any]           # return refs; refs[-1] is metadata when paired
    on_done: Callable[["_TaskRec"], None]
    tag: Any = None


@dataclass
class _StreamRec:
    """An in-flight streaming task: its generator yields block, meta,
    block, meta, ...; the executor polls it and emits a bundle per
    pair."""
    gen: Any                  # ObjectRefGenerator
    op: "PhysicalOperator"
    pending: List[Any] = field(default_factory=list)
    base_order: Tuple[int, ...] = ()  # prefix for yielded bundles' order
    item_idx: int = 0


class _OrderStager:
    """Heap of bundles keyed by logical order; releases only those no
    in-flight work can still precede (`bound` = out_min_pending)."""

    def __init__(self):
        self._heap: List[Tuple[Tuple[int, ...], int, RefBundle]] = []
        self._seq = 0

    def push(self, bundle: RefBundle) -> None:
        import heapq

        self._seq += 1
        heapq.heappush(self._heap, (bundle.order, self._seq, bundle))

    def pop_ready(self, bound: Optional[Tuple[int, ...]]
                  ) -> Iterator[RefBundle]:
        import heapq

        while self._heap and (bound is None or self._heap[0][0] < bound):
            yield heapq.heappop(self._heap)[2]

    def orders(self) -> List[Tuple[int, ...]]:
        return [o for o, _, _ in self._heap]

    def clear(self) -> None:
        self._heap.clear()

    def __len__(self) -> int:
        return len(self._heap)


class PhysicalOperator:
    def __init__(self, name: str, num_inputs: int = 1):
        self.name = name
        self.num_inputs = num_inputs
        self.in_queues = [collections.deque() for _ in range(num_inputs)]
        self.in_done = [False] * num_inputs
        self.out_queue: collections.deque = collections.deque()
        self.finished = False
        self.active = 0
        self.stats = {"tasks": 0, "rows_out": 0, "blocks_out": 0,
                      "wall_s": 0.0}
        self.downstream: List[Tuple["PhysicalOperator", int]] = []
        self.upstream: List[Optional["PhysicalOperator"]] = \
            [None] * num_inputs
        # `order` markers of in-flight work whose outputs are not yet in
        # out_queue — the ordered-consumption protocol's lower bound
        self._pending_orders: set = set()

    # -- wiring
    def connect(self, downstream: "PhysicalOperator", index: int = 0):
        self.downstream.append((downstream, index))
        downstream.upstream[index] = self

    # -- ordered consumption (reference: bundles are iterated in block
    # order; tasks complete in any order, so consumers need a lower bound
    # on what can still arrive)
    def out_min_pending(self) -> Optional[Tuple[int, ...]]:
        """Smallest `order` any output this operator has not yet handed
        downstream could carry; None = nothing more will ever come.

        Base implementation is conservative for barrier-style operators
        (AllToAll/Zip): while unfinished they may emit any order."""
        if not self.finished:
            return ()
        if self.out_queue:
            return min(b.order for b in self.out_queue)
        return None

    def _streaming_min_pending(
            self, extra=()) -> Optional[Tuple[int, ...]]:
        """min over queued inputs, in-flight work, upstream's bound and
        undelivered outputs — for operators that preserve input order."""
        cands = list(extra)
        if self.out_queue:
            cands.append(min(b.order for b in self.out_queue))
        cands.extend(self._pending_orders)
        for q in self.in_queues:
            for b in q:
                cands.append(b.order)
        for up in self.upstream:
            if up is not None:
                m = up.out_min_pending()
                if m is not None:
                    cands.append(m)
        return min(cands) if cands else None

    def _emit(self, bundle: RefBundle):
        self.stats["rows_out"] += bundle.metadata.num_rows
        self.stats["blocks_out"] += 1
        if bundle.metadata.exec_stats:
            self.stats["wall_s"] += bundle.metadata.exec_stats.get("wall_s", 0)
        self.out_queue.append(bundle)

    # -- executor interface
    def add_input(self, bundle: RefBundle, index: int = 0):
        self.in_queues[index].append(bundle)

    def notify_input_done(self, index: int = 0):
        self.in_done[index] = True

    def all_inputs_done(self) -> bool:
        return all(self.in_done)

    def has_work(self) -> bool:
        return any(self.in_queues)

    def try_submit(self, submit) -> List[_TaskRec]:
        """Submit up to one task if work is buffered; returns task recs."""
        return []

    def maybe_finish(self):
        if (self.all_inputs_done() and not self.has_work()
                and self.active == 0):
            self.finished = True


class InputOperator(PhysicalOperator):
    """Source of pre-existing bundles (materialized data)."""

    def __init__(self, bundles: List[RefBundle]):
        super().__init__("Input", num_inputs=0)
        for i, b in enumerate(bundles):
            # list position IS the logical order here; re-base every
            # bundle (copies: callers own these objects, and carried
            # keys from a prior execution must not mix with fresh ones)
            self.out_queue.append(replace(b, order=(i,)))
        self.finished = True

    def all_inputs_done(self):
        return True


class ReadOperator(PhysicalOperator):
    def __init__(self, read_tasks, chain: List[Dict], resources=None):
        super().__init__("Read", num_inputs=0)
        self._pending = collections.deque(read_tasks)
        self._chain = chain
        self._resources = resources
        self._next_idx = 0

    def all_inputs_done(self):
        return True

    def has_work(self):
        return bool(self._pending)

    def out_min_pending(self) -> Optional[Tuple[int, ...]]:
        extra = [(self._next_idx,)] if self._pending else []
        return self._streaming_min_pending(extra)

    def try_submit(self, submit) -> List[_TaskRec]:
        if not self._pending:
            return []
        rt = self._pending.popleft()
        task_idx = self._next_idx
        self._next_idx += 1
        self.active += 1
        self.stats["tasks"] += 1
        ctx = DataContext.get_current()
        if ctx.use_streaming_generators:
            gen = submit(_read_task_streaming, (rt, self._chain),
                         num_returns="streaming",
                         resources=self._resources,
                         name=f"data:{self.name}")
            self._pending_orders.add((task_idx, 0))
            return [_StreamRec(gen, self, base_order=(task_idx,))]
        refs = submit(_read_task, (rt, self._chain), num_returns=2,
                      resources=self._resources, name=f"data:{self.name}")
        self._pending_orders.add((task_idx, 0))

        def on_done(rec: _TaskRec):
            self.active -= 1
            meta = ray_tpu.get(rec.refs[1], timeout=300)
            self._emit(RefBundle(rec.refs[0], meta, order=(task_idx, 0)))
            self._pending_orders.discard((task_idx, 0))
            self.maybe_finish()

        return [_TaskRec(refs, on_done)]


class MapOperator(PhysicalOperator):
    """Fused chain of map stages; one task per input block."""

    def __init__(self, name: str, chain: List[Dict], resources=None):
        super().__init__(name)
        self._chain = chain
        self._resources = resources

    def out_min_pending(self) -> Optional[Tuple[int, ...]]:
        return self._streaming_min_pending()

    def try_submit(self, submit) -> List[_TaskRec]:
        if not self.in_queues[0]:
            return []
        bundle: RefBundle = self.in_queues[0].popleft()
        order = bundle.order
        self.active += 1
        self.stats["tasks"] += 1
        ctx = DataContext.get_current()
        if ctx.use_streaming_generators:
            gen = submit(_map_task_streaming,
                         (self._chain, bundle.block_ref),
                         num_returns="streaming",
                         resources=self._resources,
                         name=f"data:{self.name}")
            self._pending_orders.add(order + (0,))
            return [_StreamRec(gen, self, base_order=order)]
        refs = submit(_map_task, (self._chain, bundle.block_ref),
                      num_returns=2, resources=self._resources,
                      name=f"data:{self.name}")
        self._pending_orders.add(order)

        def on_done(rec: _TaskRec):
            self.active -= 1
            meta = ray_tpu.get(rec.refs[1], timeout=300)
            self._emit(RefBundle(rec.refs[0], meta, order=order))
            self._pending_orders.discard(order)
            self.maybe_finish()

        return [_TaskRec(refs, on_done)]


class _MapWorker:
    """Pool worker actor: instantiates class UDFs ONCE at startup and
    applies the fused stage chain to blocks (reference:
    actor_pool_map_operator.py _MapWorker — per-actor warm state is the
    whole point: a model loads / a program compiles once per actor, not
    once per block)."""

    def __init__(self, chain: List[Dict]):
        self._chain = []
        for s in chain:
            s = dict(s)
            if s.get("is_class"):
                s["fn"] = s["fn"](*(s.get("fn_constructor_args") or ()),
                                  **(s.get("fn_constructor_kwargs") or {}))
            self._chain.append(s)

    def apply(self, *blocks: Block):
        return _map_task(self._chain, *blocks)


class ActorPoolMapOperator(PhysicalOperator):
    """Map over an autoscaling pool of `_MapWorker` actors (reference:
    actor_pool_map_operator.py:34 ActorPoolMapOperator).

    Pool behavior: `min_size` actors are created when the operator first
    has work; while every live actor is saturated (max_tasks_in_flight
    each) and input keeps queueing, the pool grows toward `max_size`.
    Blocks route to the least-loaded ready actor.  An actor that dies
    mid-block is replaced and its in-flight blocks are resubmitted —
    tasks are retried, warm state is rebuilt by the replacement's
    __init__."""

    def __init__(self, name: str, chain: List[Dict], strategy,
                 resources=None):
        super().__init__(name)
        self._chain = chain
        self._strategy = strategy
        self._resources = resources
        # actor id -> [handle, inflight_count]
        self._actors: Dict[int, List] = {}
        self._next_actor_id = 0
        self._started = False
        self._shutdown = False
        # consecutive actor deaths with zero completed blocks in between:
        # a UDF that kills every actor it touches (bad import, OOM on
        # init) must surface, not respawn forever
        self._deaths_since_progress = 0

    # -- pool management ----------------------------------------------------

    def _spawn_actor(self):
        cls = ray_tpu.remote(_MapWorker)
        if self._resources:
            cls = cls.options(resources=dict(self._resources))
        handle = cls.remote(self._chain)
        aid = self._next_actor_id
        self._next_actor_id += 1
        self._actors[aid] = [handle, 0]
        return aid

    def _ensure_pool(self):
        if self._started:
            return
        self._started = True
        for _ in range(self._strategy.min_size):
            self._spawn_actor()

    def _pick_actor(self) -> Optional[int]:
        """Least-loaded actor below its in-flight cap; grows the pool when
        all are saturated and room remains."""
        cap = self._strategy.max_tasks_in_flight_per_actor
        candidates = [(cnt, aid) for aid, (h, cnt) in
                      self._actors.items() if cnt < cap]
        if candidates:
            return min(candidates)[1]
        if len(self._actors) < self._strategy.max_size:
            return self._spawn_actor()
        return None

    def _replace_actor(self, aid: int):
        info = self._actors.pop(aid, None)
        if info is None:
            return  # another in-flight task of the same actor got here
        try:
            ray_tpu.kill(info[0], no_restart=True)
        except Exception:
            pass
        if not self._shutdown:
            self._spawn_actor()

    def _maybe_shutdown_pool(self):
        if self._shutdown:
            return
        self._shutdown = True
        for aid, (h, cnt) in list(self._actors.items()):
            try:
                ray_tpu.kill(h, no_restart=True)
            except Exception:
                pass
        self._actors.clear()

    # -- operator interface -------------------------------------------------

    def out_min_pending(self) -> Optional[Tuple[int, ...]]:
        return self._streaming_min_pending()

    def try_submit(self, submit) -> List[_TaskRec]:
        # at most one submission per call: the executor accounts its
        # global budget / per-op caps per try_submit round (MapOperator
        # keeps the same discipline)
        if not self.in_queues[0]:
            return []
        self._ensure_pool()
        aid = self._pick_actor()
        if aid is None:
            return []
        bundle: RefBundle = self.in_queues[0].popleft()
        return [self._submit_to(aid, bundle)]

    def _submit_to(self, aid: int, bundle: RefBundle) -> _TaskRec:
        handle = self._actors[aid][0]
        self._actors[aid][1] += 1
        self.active += 1
        self.stats["tasks"] += 1
        order = bundle.order
        self._pending_orders.add(order)
        refs = handle.apply.options(num_returns=2).remote(bundle.block_ref)

        def on_done(rec: _TaskRec):
            self.active -= 1
            if aid in self._actors:
                self._actors[aid][1] -= 1
            try:
                meta = ray_tpu.get(rec.refs[1], timeout=300)
            except (ray_tpu.ActorDiedError,
                    ray_tpu.WorkerCrashedError) as e:
                self._pending_orders.discard(order)
                self._deaths_since_progress += 1
                if self._deaths_since_progress > \
                        2 * max(2, self._strategy.max_size):
                    raise RuntimeError(
                        f"{self.name}: actor pool is dying faster than it "
                        f"completes work ({self._deaths_since_progress} "
                        f"consecutive deaths) — the UDF or its imports "
                        f"likely crash the worker; last: {e}") from e
                # replace the dead actor, resubmit this block: retried
                # work re-enters the input queue so the normal submit
                # path (with a fresh pool member) picks it up
                self._replace_actor(aid)
                self.in_queues[0].appendleft(bundle)
                return
            self._deaths_since_progress = 0
            self._emit(RefBundle(rec.refs[0], meta, order=order))
            self._pending_orders.discard(order)
            self.maybe_finish()

        return _TaskRec(list(refs), on_done, tag=aid)

    def maybe_finish(self):
        super().maybe_finish()
        if self.finished:
            self._maybe_shutdown_pool()

    # introspection for tests
    def pool_size(self) -> int:
        return len(self._actors)


class LimitOperator(PhysicalOperator):
    """Row-limit in DATASET order: blocks complete out of order, so input
    is staged in an order-heap and consumed only once no earlier block can
    still arrive (upstream.out_min_pending) — limit(5) must keep the first
    5 rows of the dataset, not of whichever task finished first."""

    def __init__(self, limit: int):
        super().__init__(f"Limit({limit})")
        self._remaining = limit
        self._buf = _OrderStager()

    def has_work(self) -> bool:
        return any(self.in_queues) or bool(len(self._buf))

    def out_min_pending(self) -> Optional[Tuple[int, ...]]:
        return self._streaming_min_pending(self._buf.orders())

    def try_submit(self, submit) -> List[_TaskRec]:
        while self.in_queues[0]:
            self._buf.push(self.in_queues[0].popleft())
        up = self.upstream[0]
        upmin = up.out_min_pending() if up is not None else None
        recs = []
        for bundle in self._buf.pop_ready(upmin):
            if self._remaining <= 0:
                break
            n = bundle.metadata.num_rows
            if n <= self._remaining:
                self._remaining -= n
                self._emit(bundle)
                continue
            take = self._remaining
            self._remaining = 0
            order = bundle.order
            refs = submit(_slice_task, (take, bundle.block_ref),
                          num_returns=2, name=f"data:{self.name}")
            self.active += 1
            self.stats["tasks"] += 1
            self._pending_orders.add(order)

            def on_done(rec: _TaskRec):
                self.active -= 1
                meta = ray_tpu.get(rec.refs[1], timeout=300)
                self._emit(RefBundle(rec.refs[0], meta, order=order))
                self._pending_orders.discard(order)
                self.maybe_finish()

            recs.append(_TaskRec(refs, on_done))
        if self._remaining == 0:
            # drop any remaining input; upstream stops via executor check
            for q in self.in_queues:
                q.clear()
            self._buf.clear()
            if self.active == 0:
                self.finished = True
        else:
            self.maybe_finish()
        return recs

    def satisfied(self) -> bool:
        return self._remaining <= 0

    def maybe_finish(self):
        if self.satisfied() and self.active == 0:
            self.finished = True
            return
        super().maybe_finish()


class UnionOperator(PhysicalOperator):
    def __init__(self, n: int):
        super().__init__("Union", num_inputs=n)

    def out_min_pending(self) -> Optional[Tuple[int, ...]]:
        cands = []
        if self.out_queue:
            cands.append(min(b.order for b in self.out_queue))
        for side in range(self.num_inputs):
            side_c = [(side,) + b.order for b in self.in_queues[side]]
            up = self.upstream[side]
            if up is not None:
                m = up.out_min_pending()
                if m is not None:
                    side_c.append((side,) + m)
            cands.extend(side_c)
        return min(cands) if cands else None

    def try_submit(self, submit) -> List[_TaskRec]:
        for side, q in enumerate(self.in_queues):
            while q:
                b = q.popleft()
                # re-base a COPY: side-0 rows precede side-1 rows; the
                # original object may be shared with another consumer
                # (diamond DAG) whose sort keys must not change
                self._emit(replace(b, order=(side,) + b.order))
        self.maybe_finish()
        return []


class ZipOperator(PhysicalOperator):
    """Barrier: buffers both sides, then zips row-aligned slices."""

    def __init__(self):
        super().__init__("Zip", num_inputs=2)
        self._left: List[RefBundle] = []
        self._right: List[RefBundle] = []
        self._planned = False

    def has_work(self) -> bool:
        # buffered-but-unplanned bundles are work: without this the
        # done-propagation sweep sees empty in_queues + active==0 and
        # finishes the op before it ever plans (zip returned 0 rows)
        return super().has_work() or (
            bool(self._left or self._right) and not self._planned)

    def try_submit(self, submit) -> List[_TaskRec]:
        while self.in_queues[0]:
            self._left.append(self.in_queues[0].popleft())
        while self.in_queues[1]:
            self._right.append(self.in_queues[1].popleft())
        if not (self.in_done[0] and self.in_done[1]) or self._planned:
            self.maybe_finish()
            return []
        self._planned = True
        # arrival order is completion order; row alignment needs logical
        # order (the flake: zip under load paired id 5-9 with other 100-104)
        self._left.sort(key=lambda b: b.order)
        self._right.sort(key=lambda b: b.order)
        lrows = sum(b.metadata.num_rows for b in self._left)
        rrows = sum(b.metadata.num_rows for b in self._right)
        if lrows != rrows:
            raise ValueError(
                f"zip(): datasets have different row counts: {lrows} vs "
                f"{rrows}")
        # For each left block, find overlapping right slices.
        r_offsets = []
        off = 0
        for b in self._right:
            r_offsets.append(off)
            off += b.metadata.num_rows
        recs = []
        l_off = 0
        for lb in self._left:
            ln = lb.metadata.num_rows
            slices = []
            need_start, need_end = l_off, l_off + ln
            for ri, rb in enumerate(self._right):
                rs = r_offsets[ri]
                re = rs + rb.metadata.num_rows
                s = max(need_start, rs)
                e = min(need_end, re)
                if s < e:
                    slices.append((ri, s - rs, e - s))
            # compact indices to the refs we pass
            idx_map = {}
            cslices = []
            crefs = []
            for (ri, st, lnn) in slices:
                if ri not in idx_map:
                    idx_map[ri] = len(crefs)
                    crefs.append(self._right[ri].block_ref)
                cslices.append((idx_map[ri], st, lnn))
            refs = submit(_zip_task,
                          (l_off, cslices, lb.block_ref, *crefs),
                          num_returns=2, name="data:Zip")
            self.active += 1
            self.stats["tasks"] += 1

            def on_done(rec: _TaskRec, order=lb.order):
                self.active -= 1
                meta = ray_tpu.get(rec.refs[1], timeout=300)
                self._emit(RefBundle(rec.refs[0], meta, order=order))
                self.maybe_finish()

            recs.append(_TaskRec(refs, on_done))
            l_off += ln
        self.maybe_finish()
        return recs


class AllToAllOperator(PhysicalOperator):
    """Barrier op: partition phase fans each input block into N parts;
    reduce phase combines part i of every block into output block i
    (reference: _internal/planner/exchange/)."""

    def __init__(self, name: str, kind: str, *, num_outputs=None, key=None,
                 descending=False, seed=None, aggs=None, fn=None,
                 batch_format=None, shuffle_blocks=False):
        super().__init__(name)
        self.kind = kind
        self.num_outputs = num_outputs
        self.key = key
        self.descending = descending
        self.seed = seed
        self.aggs = aggs
        self.fn = fn
        self.batch_format = batch_format
        self.shuffle_blocks = shuffle_blocks
        self._bundles: List[RefBundle] = []
        self._phase = "collect"
        self._samples: List[Any] = []
        self._sample_refs: List[Any] = []
        self._boundaries = None
        self._parts: List[List[Any]] = []  # [input][partition] -> ref
        self._n_parts_done = 0

    def _resolved_num_outputs(self) -> int:
        if self.kind in ("groupby", "map_groups") and self.key is None:
            return 1
        if self.num_outputs:
            return self.num_outputs
        ctx = DataContext.get_current()
        if ctx.default_shuffle_partitions:
            return ctx.default_shuffle_partitions
        return max(1, len(self._bundles))

    def try_submit(self, submit) -> List[_TaskRec]:
        while self.in_queues[0]:
            self._bundles.append(self.in_queues[0].popleft())
        if not self.all_inputs_done():
            return []
        if self._phase == "collect":
            # logical order, not arrival order: repartition concatenates
            # part j of every input in _bundles order, so row order must
            # match the upstream's (sort/shuffle are insensitive but
            # repartition-then-zip is not)
            self._bundles.sort(key=lambda b: b.order)
            if self.kind in ("sort", "groupby_sort"):
                self._phase = "sample"
            else:
                self._phase = "partition"
        recs: List[_TaskRec] = []
        if self._phase == "sample":
            self._phase = "sampling"
            for b in self._bundles:
                refs = submit(_sample_task, (self.key, 8, b.block_ref),
                              num_returns=1, name=f"data:{self.name}:sample")
                self.active += 1

                def on_done(rec: _TaskRec):
                    self.active -= 1
                    self._samples.extend(ray_tpu.get(rec.refs[0],
                                                     timeout=300))
                    if self.active == 0:
                        self._compute_boundaries()
                        self._phase = "partition"

                recs.append(_TaskRec(refs, on_done))
            if not recs:  # no input blocks at all
                self._phase = "partition"
        if self._phase == "partition":
            self._phase = "reduce_wait"
            n = self._resolved_num_outputs()
            if not self._bundles:
                self.finished = True
                return recs
            spec = self._partition_spec(n)
            starts = None
            if spec["how"] == "contig":
                total = sum(b.metadata.num_rows for b in self._bundles)
                spec["cuts"] = [round(total * j / n) for j in range(n + 1)]
                starts, off = [], 0
                for b in self._bundles:
                    starts.append(off)
                    off += b.metadata.num_rows
            self._parts = [None] * len(self._bundles)
            for i, b in enumerate(self._bundles):
                bspec = spec if starts is None else dict(spec,
                                                        start=starts[i])
                refs = submit(_partition_task, (bspec, b.block_ref),
                              num_returns=n, name=f"data:{self.name}:part")
                self.active += 1
                self.stats["tasks"] += 1

                def on_done(rec: _TaskRec, i=i):
                    self.active -= 1
                    self._parts[i] = rec.refs
                    self._n_parts_done += 1
                    if self._n_parts_done == len(self._bundles):
                        self._phase = "reduce"

                recs.append(_TaskRec(refs, on_done))
        if self._phase == "reduce":
            self._phase = "done_wait"
            n = self._resolved_num_outputs()
            rspec = self._reduce_spec()
            order = list(range(n))
            if self.kind == "shuffle" and self.shuffle_blocks:
                rng = np.random.RandomState(self.seed)
                rng.shuffle(order)
            for rank, j in enumerate(order):
                part_refs = [self._parts[i][j]
                             for i in range(len(self._bundles))]
                refs = submit(_reduce_task, (rspec, *part_refs),
                              num_returns=2,
                              name=f"data:{self.name}:reduce")
                self.active += 1
                self.stats["tasks"] += 1

                def on_done(rec: _TaskRec, rank=rank):
                    self.active -= 1
                    meta = ray_tpu.get(rec.refs[1], timeout=300)
                    # rank is the output's logical position (sorted range
                    # j for sort; the shuffled sequence for shuffle)
                    self._emit(RefBundle(rec.refs[0], meta, order=(rank,)))
                    if self.active == 0 and self._phase == "done_wait":
                        self.finished = True

                recs.append(_TaskRec(refs, on_done))
        return recs

    def _compute_boundaries(self):
        n = self._resolved_num_outputs()
        if not self._samples:
            self._boundaries = []
            return
        qs = np.linspace(0, 1, n + 1)[1:-1]
        self._boundaries = list(np.quantile(
            np.asarray(sorted(self._samples)), qs, method="nearest")) \
            if len(qs) else []

    def _partition_spec(self, n: int) -> Dict:
        if self.kind == "shuffle":
            return {"how": "random", "n": n, "seed": self.seed}
        if self.kind == "repartition":
            return {"how": "contig", "n": n}  # cuts/start added at phase
        if self.kind in ("groupby", "map_groups"):
            if self.key is None:
                return {"how": "round_robin", "n": 1}
            return {"how": "hash", "n": n, "key": self.key}
        if self.kind == "sort":
            return {"how": "range", "n": n, "key": self.key,
                    "boundaries": self._boundaries or [],
                    "descending": self.descending}
        raise ValueError(self.kind)

    def _reduce_spec(self) -> Dict:
        if self.kind == "shuffle":
            return {"how": "shuffle", "seed": self.seed}
        if self.kind == "repartition":
            return {"how": "concat"}
        if self.kind == "sort":
            return {"how": "sort", "key": self.key,
                    "descending": self.descending}
        if self.kind == "groupby":
            return {"how": "aggregate", "key": self.key, "aggs": self.aggs}
        if self.kind == "map_groups":
            return {"how": "map_groups", "key": self.key, "fn": self.fn,
                    "batch_format": self.batch_format}
        raise ValueError(self.kind)

    def maybe_finish(self):
        # completion handled by phases
        if (self.all_inputs_done() and not self._bundles
                and self._phase == "collect"):
            self.finished = True


# ---------------------------------------------------------------------------
# Planner: logical DAG -> physical DAG

def _stage_of(op: L.AbstractMap) -> Dict:
    # stage fns travel as task/actor-constructor ARGS (not as the remote
    # function itself), so the by-value registration that ray_tpu.remote
    # applies to its target never sees them — a UDF class defined in a
    # driver-only module would hit ModuleNotFoundError on the worker
    from ray_tpu._private.common import _ensure_picklable_by_value

    _ensure_picklable_by_value(op.fn)
    stage = {"kind": op.fn_kind, "fn": op.fn, "batch_size": op.batch_size,
             "batch_format": op.batch_format, "fn_args": op.fn_args,
             "fn_kwargs": op.fn_kwargs}
    if getattr(op, "is_class_udf", False):
        stage["is_class"] = True
        stage["fn_constructor_args"] = getattr(op, "fn_constructor_args", ())
        stage["fn_constructor_kwargs"] = getattr(op, "fn_constructor_kwargs",
                                                 None)
    return stage


def plan(logical_dag: L.LogicalOp
         ) -> Tuple[PhysicalOperator, List[PhysicalOperator]]:
    """Build the physical DAG, fusing chains of AbstractMap into single
    operators (the reference's OperatorFusionRule).  Returns (sink, ops)."""
    ctx = DataContext.get_current()
    ops: List[PhysicalOperator] = []

    # Count consumers of every logical node: a shared (diamond) subtree must
    # build exactly ONE physical operator (else nondeterministic shared ops
    # like unseeded shuffles diverge per branch), and fusion into a shared
    # upstream is forbidden (it would apply one consumer's stages to all).
    consumers: Dict[int, int] = {}

    def count(op: L.LogicalOp):
        for parent in getattr(op, "inputs", ()):
            consumers[id(parent)] = consumers.get(id(parent), 0) + 1
            if consumers[id(parent)] == 1:
                count(parent)

    count(logical_dag)
    memo: Dict[int, PhysicalOperator] = {}

    def register(phys: PhysicalOperator) -> PhysicalOperator:
        if phys not in ops:
            ops.append(phys)
        return phys

    def build(op: L.LogicalOp) -> PhysicalOperator:
        if id(op) in memo:
            return memo[id(op)]
        phys = register(_build(op))
        memo[id(op)] = phys
        return phys

    def _build(op: L.LogicalOp) -> PhysicalOperator:
        if isinstance(op, L.InputData):
            return InputOperator(op.bundles)
        if isinstance(op, L.Read):
            parallelism = op.parallelism
            if parallelism is None or parallelism < 0:
                parallelism = 200
            tasks = op.datasource.get_read_tasks(parallelism)
            return ReadOperator(tasks, chain=[])
        if isinstance(op, L.AbstractMap):
            upstream = build(op.inputs[0])
            stage = _stage_of(op)
            resources = op.resources or None
            from .compute import ActorPoolStrategy, TaskPoolStrategy

            strategy = op.compute
            wants_actors = isinstance(strategy, ActorPoolStrategy)
            # fuse into upstream Read / Map when compatible — but never
            # into a node other consumers also read (diamond DAGs), and
            # never when the user capped THIS stage's concurrency (fusing
            # would run it at the upstream's parallelism instead)
            capped = isinstance(strategy, TaskPoolStrategy) \
                and strategy.size is not None
            fusable = consumers.get(id(op.inputs[0]), 0) <= 1 \
                and not capped
            if wants_actors:
                # actor compute is its own operator; a later fusable
                # plain-map stage may fuse INTO it (runs on the actors),
                # but an actor stage never fuses into a task upstream
                phys = ActorPoolMapOperator(op.name, [stage], strategy,
                                            resources=resources)
                upstream.connect(phys, 0)
                return phys
            if fusable and isinstance(upstream, ActorPoolMapOperator) \
                    and not resources:
                upstream._chain.append(stage)
                upstream.name = f"{upstream.name}->{op.name}"
                return upstream
            if fusable and isinstance(upstream, ReadOperator) \
                    and not resources:
                upstream._chain.append(stage)
                upstream.name = f"{upstream.name}->{op.name}"
                return upstream
            if fusable and isinstance(upstream, MapOperator) and \
                    upstream._resources == resources:
                upstream._chain.append(stage)
                upstream.name = f"{upstream.name}->{op.name}"
                return upstream
            phys = MapOperator(op.name, [stage], resources=resources)
            if capped:
                phys.task_cap = strategy.size
            upstream.connect(phys, 0)
            return phys
        if isinstance(op, L.Limit):
            upstream = build(op.inputs[0])
            phys = LimitOperator(op.limit)
            upstream.connect(phys, 0)
            return phys
        if isinstance(op, L.Union):
            phys = UnionOperator(len(op.inputs))
            for i, parent in enumerate(op.inputs):
                build(parent).connect(phys, i)
            return phys
        if isinstance(op, L.Zip):
            phys = ZipOperator()
            build(op.inputs[0]).connect(phys, 0)
            build(op.inputs[1]).connect(phys, 1)
            return phys
        if isinstance(op, L.Repartition):
            upstream = build(op.inputs[0])
            phys = AllToAllOperator(
                f"Repartition({op.num_outputs})",
                "shuffle" if op.shuffle else "repartition",
                num_outputs=op.num_outputs)
            upstream.connect(phys, 0)
            return phys
        if isinstance(op, L.RandomShuffle):
            upstream = build(op.inputs[0])
            seed = op.seed if op.seed is not None else ctx.seed
            phys = AllToAllOperator("RandomShuffle", "shuffle",
                                    num_outputs=op.num_outputs, seed=seed,
                                    shuffle_blocks=True)
            upstream.connect(phys, 0)
            return phys
        if isinstance(op, L.Sort):
            upstream = build(op.inputs[0])
            phys = AllToAllOperator(f"Sort({op.key})", "sort",
                                    num_outputs=op.num_outputs, key=op.key,
                                    descending=op.descending)
            upstream.connect(phys, 0)
            return phys
        if isinstance(op, L.GroupByAggregate):
            upstream = build(op.inputs[0])
            phys = AllToAllOperator(f"Aggregate({op.key})", "groupby",
                                    num_outputs=op.num_outputs, key=op.key,
                                    aggs=op.aggs)
            upstream.connect(phys, 0)
            return phys
        if isinstance(op, L.MapGroups):
            upstream = build(op.inputs[0])
            phys = AllToAllOperator(f"MapGroups({op.key})", "map_groups",
                                    num_outputs=op.num_outputs, key=op.key,
                                    fn=op.fn, batch_format=op.batch_format)
            upstream.connect(phys, 0)
            return phys
        raise TypeError(f"unknown logical op {op!r}")

    sink = build(logical_dag)
    return sink, ops


class StreamingExecutor:
    """Pull-based streaming scheduling loop (reference:
    streaming_executor.py:48 + streaming_executor_state.py
    select_operator_to_run)."""

    def __init__(self, sink: PhysicalOperator, all_ops: List[PhysicalOperator]):
        self.sink = sink
        self.ops = all_ops
        self.ctx = DataContext.get_current()
        self._inflight: Dict[str, Tuple[_TaskRec, Any]] = {}
        self._streams: List[_StreamRec] = []
        self._started = time.perf_counter()
        self.wall_s = 0.0

    def _submit(self, fn, args, *, num_returns=1, resources=None, name=""):
        res = dict(self.ctx.task_resources or {})
        if resources:
            res.update(resources)  # per-operator demands win
        opts = dict(num_returns=num_returns, name=name,
                    resources=res or None, num_cpus=1)
        if num_returns == "streaming":
            opts["_generator_backpressure_num_objects"] = \
                self.ctx.generator_backpressure_num_objects
        remote_fn = ray_tpu.remote(fn).options(**opts)
        refs = remote_fn.remote(*args)
        if num_returns == 1:
            refs = [refs]
        return refs  # an ObjectRefGenerator when streaming

    def _track(self, rec, op: PhysicalOperator):
        if isinstance(rec, _StreamRec):
            self._streams.append(rec)
        else:
            self._inflight[rec.refs[0].id] = (rec, op)

    def _poll_streams(self) -> bool:
        from ray_tpu import GetTimeoutError

        progressed = False
        for srec in list(self._streams):
            while True:
                try:
                    ref = srec.gen.next_ready(timeout=0)
                except StopIteration:
                    srec.op.active -= 1
                    srec.op._pending_orders.discard(
                        srec.base_order + (srec.item_idx,))
                    srec.op.maybe_finish()
                    self._streams.remove(srec)
                    progressed = True
                    break
                except GetTimeoutError:
                    break
                srec.pending.append(ref)
                if len(srec.pending) == 2:
                    block_ref, meta_ref = srec.pending
                    srec.pending = []
                    meta = ray_tpu.get(meta_ref, timeout=300)
                    srec.op._emit(RefBundle(
                        block_ref, meta,
                        order=srec.base_order + (srec.item_idx,)))
                    srec.op._pending_orders.discard(
                        srec.base_order + (srec.item_idx,))
                    srec.item_idx += 1
                    srec.op._pending_orders.add(
                        srec.base_order + (srec.item_idx,))
                    progressed = True
        return progressed

    def _route_outputs(self, op: PhysicalOperator):
        while op.out_queue:
            bundle = op.out_queue.popleft()
            if not op.downstream:
                yield bundle
                continue
            for (d, idx) in op.downstream:
                d.add_input(bundle, idx)

    def _propagate_done(self):
        for op in self.ops:
            if op.finished or (op.all_inputs_done() and not op.has_work()
                               and op.active == 0):
                op.maybe_finish()
                if op.finished or isinstance(op, (InputOperator,)):
                    for (d, idx) in op.downstream:
                        if not d.in_done[idx] and not op.out_queue:
                            d.notify_input_done(idx)

    def _limit_reached(self) -> bool:
        return isinstance(self.sink, LimitOperator) and self.sink.satisfied()

    def run(self) -> Iterator[RefBundle]:
        """Generator over output bundles of the sink."""
        try:
            yield from self._run_loop()
        finally:
            self.wall_s = time.perf_counter() - self._started
            # abnormal exit (crash-loop RuntimeError, UDF exception, or
            # the consumer abandoning the generator) must not leak pool
            # actors — including replacements just spawned for dead ones
            for op in self.ops:
                shutdown = getattr(op, "_maybe_shutdown_pool", None)
                if shutdown is not None:
                    try:
                        shutdown()
                    except Exception:
                        pass

    def _run_loop(self) -> Iterator[RefBundle]:
        # preserve_order: outputs stage in an order-heap and yield only
        # when no smaller order can still arrive (sink.out_min_pending)
        ordered = self.ctx.preserve_order
        out_buffer: collections.deque = collections.deque()
        out_heap = _OrderStager()
        while True:
            progressed = False
            # 1. submissions
            budget = (self.ctx.max_concurrent_tasks - len(self._inflight)
                      - len(self._streams))
            # out_heap is NOT counted in plain backpressure: its bundles
            # are held back waiting for a straggler's smaller order —
            # counting them would freeze submissions (including the
            # straggler's) into a deadlock.  But unbounded staging pins
            # every staged block in the object store, so past a cap only
            # operators that can still produce an order <= the blocking
            # one (the straggler's lineage) may submit.
            backpressured = (len(out_buffer)
                            >= self.ctx.max_buffered_output_bundles)
            blocking_order = None
            if ordered and len(out_heap) >= \
                    4 * self.ctx.max_buffered_output_bundles \
                    and out_heap._heap:
                blocking_order = out_heap._heap[0][0]
            if budget > 0 and not backpressured and not self._limit_reached():
                for op in self.ops:
                    if budget <= 0:
                        break
                    if blocking_order is not None:
                        m = op.out_min_pending()
                        if m is None or m > blocking_order:
                            continue
                    percap = self.ctx.max_tasks_per_operator
                    if percap is not None and op.active >= percap:
                        continue
                    opcap = getattr(op, "task_cap", None)
                    if opcap is not None and op.active >= opcap:
                        continue
                    recs = op.try_submit(
                        lambda fn, args, **kw: self._submit(fn, args, **kw))
                    for rec in recs:
                        self._track(rec, op)
                        budget -= 1
                        progressed = True
            else:
                # even without budget, zero-task ops (limit/union) progress
                for op in self.ops:
                    if isinstance(op, (LimitOperator, UnionOperator,
                                       ZipOperator)) and op.has_work():
                        recs = op.try_submit(
                            lambda fn, args, **kw: self._submit(fn, args,
                                                                **kw))
                        for rec in recs:
                            self._track(rec, op)
                            progressed = True
            # 2. completions
            if self._inflight:
                first_refs = [rec.refs[0] for rec, _ in
                              self._inflight.values()]
                ready, _ = ray_tpu.wait(
                    first_refs, num_returns=len(first_refs), timeout=0.05)
                for r in ready:
                    rec, op = self._inflight.pop(r.id)
                    rec.on_done(rec)
                    progressed = True
            # 2b. streamed items: a bundle per (block, meta) pair, as
            # soon as the producer reports them (bounded memory — blocks
            # never buffer inside tasks)
            if self._poll_streams():
                progressed = True
            # 3. route outputs downstream / to the consumer
            for op in self.ops:
                for bundle in self._route_outputs(op):
                    if ordered:
                        out_heap.push(bundle)
                    else:
                        out_buffer.append(bundle)
            while out_buffer:
                progressed = True
                yield out_buffer.popleft()
            if len(out_heap):
                for bundle in out_heap.pop_ready(
                        self.sink.out_min_pending()):
                    progressed = True
                    yield bundle
            # 4. done propagation
            self._propagate_done()
            if self.sink.finished and not self._inflight and \
                    not self._streams and not self.sink.out_queue:
                for op in self.ops:
                    for bundle in self._route_outputs(op):
                        out_heap.push(bundle)
                yield from out_heap.pop_ready(None)
                return
            if self._limit_reached() and not self._inflight:
                self.sink.maybe_finish()
                if self.sink.finished:
                    return
            if not progressed:
                time.sleep(0.002)

    def stats_summary(self) -> str:
        lines = []
        for op in self.ops:
            s = op.stats
            lines.append(
                f"{op.name}: {s['tasks']} tasks, {s['blocks_out']} blocks, "
                f"{s['rows_out']} rows, {s['wall_s']:.3f}s task-time")
        lines.append(f"total wall: {self.wall_s:.3f}s")
        return "\n".join(lines)


def build_executor(logical_dag: L.LogicalOp) -> StreamingExecutor:
    sink, ops = plan(logical_dag)
    return StreamingExecutor(sink, ops)
