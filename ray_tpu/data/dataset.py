"""Dataset: the lazy, distributed data abstraction.

Reference: python/ray/data/dataset.py (Dataset.map_batches :391,
iter_batches :3820, materialize :4768).  A Dataset is a logical plan; all
transforms append logical ops; consumption plans + runs the streaming
executor (execution.py) over ray_tpu tasks.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

import ray_tpu

from . import aggregate as agg_mod
from . import logical as L
from .block import Block, BlockAccessor, BlockMetadata, batch_to_block
from .context import DataContext
from .datasource import write_block
from .execution import RefBundle, StreamingExecutor, build_executor
from .iterator import iter_block_batches, iter_jax_batches, prefetch_iter


def _slice_block_task(block: Block, start: int, length: int) -> Block:
    return BlockAccessor(block).to_arrow().slice(start, length)


class Dataset:
    def __init__(self, dag: L.LogicalOp):
        self._dag = dag
        self._last_stats: Optional[str] = None

    # ------------------------------------------------------------------
    # transforms (lazy)

    def _with(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(op)

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: Optional[str] = None, fn_args=(),
                    fn_kwargs=None, fn_constructor_args=(),
                    fn_constructor_kwargs=None,
                    compute=None, concurrency=None,
                    zero_copy_batch: bool = False,
                    num_cpus: Optional[float] = None,
                    num_tpus: Optional[float] = None,
                    resources: Optional[Dict[str, float]] = None
                    ) -> "Dataset":
        """Unknown keyword arguments raise TypeError (no silent-ignore
        catch-all): a user porting an unsupported reference kwarg must
        hear about it, not get silently different behavior.

        A callable CLASS `fn` runs on an actor pool: instances are
        constructed once per pool actor (`fn_constructor_args/kwargs`)
        and reused for every batch — pass `concurrency=n` for a fixed
        pool or `(min, max)` for autoscaling (reference: dataset.py
        map_batches `concurrency` + compute.py ActorPoolStrategy).
        `zero_copy_batch` is accepted as a hint (numpy batches here are
        already zero-copy views over shm blocks)."""
        import inspect

        from .compute import ActorPoolStrategy, strategy_from_concurrency

        resources = dict(resources or {})
        if num_cpus:
            resources["CPU"] = num_cpus
        if num_tpus:
            resources["TPU"] = num_tpus
        is_class = inspect.isclass(fn)
        if not is_class and (fn_constructor_args or fn_constructor_kwargs):
            raise ValueError(
                "fn_constructor_args/kwargs are only valid with a "
                "callable-class UDF")
        if compute is None:
            compute = strategy_from_concurrency(concurrency, is_class)
        elif concurrency is not None:
            raise ValueError("pass `compute` or `concurrency`, not both")
        elif is_class and not isinstance(compute, ActorPoolStrategy):
            raise ValueError(
                "a callable-class UDF requires ActorPoolStrategy compute")
        ctx = DataContext.get_current()
        op = L.MapBatches(
            self._dag, fn, batch_size=batch_size,
            batch_format=batch_format or ctx.default_batch_format,
            fn_args=fn_args, fn_kwargs=fn_kwargs, compute=compute,
            resources=resources or None)
        op.is_class_udf = is_class
        op.fn_constructor_args = tuple(fn_constructor_args or ())
        op.fn_constructor_kwargs = fn_constructor_kwargs or {}
        if is_class:
            op.name = f"MapBatches({fn.__name__})"
        return self._with(op)

    def _row_op(self, cls, fn: Callable, concurrency, compute,
                resources) -> "Dataset":
        import inspect

        from .compute import ActorPoolStrategy, strategy_from_concurrency

        is_class = inspect.isclass(fn)
        if compute is None:
            compute = strategy_from_concurrency(concurrency, is_class)
        elif concurrency is not None:
            raise ValueError("pass `compute` or `concurrency`, not both")
        elif is_class and not isinstance(compute, ActorPoolStrategy):
            raise ValueError(
                "a callable-class UDF requires ActorPoolStrategy compute")
        op = cls(self._dag, fn, compute=compute,
                 resources=dict(resources or {}) or None)
        op.is_class_udf = is_class
        return self._with(op)

    def map(self, fn: Callable, *, concurrency=None, compute=None,
            resources=None) -> "Dataset":
        return self._row_op(L.MapRows, fn, concurrency, compute, resources)

    def filter(self, fn: Callable, *, concurrency=None, compute=None,
               resources=None) -> "Dataset":
        return self._row_op(L.Filter, fn, concurrency, compute, resources)

    def flat_map(self, fn: Callable, *, concurrency=None, compute=None,
                 resources=None) -> "Dataset":
        return self._row_op(L.FlatMap, fn, concurrency, compute, resources)

    def add_column(self, name: str, fn: Callable[[Any], Any]) -> "Dataset":
        def add(batch: Dict[str, np.ndarray], _name=name, _fn=fn):
            batch = dict(batch)
            batch[_name] = np.asarray(_fn(batch))
            return batch

        return self._with(L.MapBatches(self._dag, add, batch_format="numpy",
                                       name=f"AddColumn({name})"))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(block: Block, _cols=tuple(cols)):
            return BlockAccessor(block).drop(list(_cols))

        return self._with(L.MapBlocks(self._dag, drop,
                                      name=f"DropColumns({cols})"))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(block: Block, _cols=tuple(cols)):
            return BlockAccessor(block).select(list(_cols))

        return self._with(L.MapBlocks(self._dag, select,
                                      name=f"SelectColumns({cols})"))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def rename(block: Block, _m=dict(mapping)):
            return BlockAccessor(block).rename(_m)

        return self._with(L.MapBlocks(self._dag, rename, name="Rename"))

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        def sample(block: Block, _frac=fraction, _seed=seed):
            acc = BlockAccessor(block)
            rng = np.random.RandomState(_seed)
            mask = rng.random_sample(acc.num_rows()) < _frac
            return acc.take(np.nonzero(mask)[0].tolist())

        return self._with(L.MapBlocks(self._dag, sample, name="Sample"))

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(self._dag, n))

    def repartition(self, num_blocks: int, shuffle: bool = False) -> "Dataset":
        return self._with(L.Repartition(self._dag, num_blocks, shuffle))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        return self._with(L.RandomShuffle(self._dag, seed=seed,
                                          num_outputs=num_blocks))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(L.Sort(self._dag, key, descending))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(L.Union([self._dag] + [o._dag for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(L.Zip(self._dag, other._dag))

    def groupby(self, key: Optional[str]) -> "GroupedData":
        from .grouped import GroupedData

        return GroupedData(self, key)

    # ------------------------------------------------------------------
    # execution

    def _execute(self) -> Iterator[RefBundle]:
        executor = build_executor(self._dag)
        try:
            yield from executor.run()
        finally:
            self._last_stats = executor.stats_summary()

    def iter_internal_ref_bundles(self) -> Iterator[RefBundle]:
        return self._execute()

    def materialize(self) -> "MaterializedDataset":
        bundles = list(self._execute())
        return MaterializedDataset(bundles, stats=self._last_stats)

    def stats(self) -> str:
        return self._last_stats or "(not executed)"

    # ------------------------------------------------------------------
    # consumption

    def take(self, limit: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for bundle in self.limit(limit)._execute():
            block = ray_tpu.get(bundle.block_ref, timeout=600)
            for row in BlockAccessor(block).iter_rows():
                out.append(row)
                if len(out) >= limit:
                    return out
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for bundle in self._execute():
            block = ray_tpu.get(bundle.block_ref, timeout=600)
            out.extend(BlockAccessor(block).iter_rows())
        return out

    def count(self) -> int:
        # metadata fast path (reference: Dataset.count's parquet-footer
        # shortcut): a bare Read whose datasource knows its EXACT row
        # count answers without executing a single read task
        if type(self._dag) is L.Read:
            n = self._dag.datasource.plan_row_count()
            if n is not None:
                return n
        return sum(b.metadata.num_rows for b in self._execute())

    def num_blocks(self) -> int:
        """Block count (reference: Dataset.num_blocks — execution-backed
        on a lazy dataset; MaterializedDataset answers from its refs)."""
        return sum(1 for _ in self._execute())

    def schema(self) -> Optional[pa.Schema]:
        for bundle in self.limit(1)._execute():
            if bundle.metadata.schema is not None:
                return bundle.metadata.schema
            block = ray_tpu.get(bundle.block_ref, timeout=600)
            return BlockAccessor(block).schema()
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def to_pandas(self):
        import pandas as pd

        parts = []
        for bundle in self._execute():
            block = ray_tpu.get(bundle.block_ref, timeout=600)
            parts.append(BlockAccessor(block).to_pandas())
        if not parts:
            return pd.DataFrame()
        return pd.concat(parts, ignore_index=True)

    def to_arrow(self) -> pa.Table:
        blocks = [ray_tpu.get(b.block_ref, timeout=600)
                  for b in self._execute()]
        return BlockAccessor.concat(blocks)

    def unique(self, column: str) -> List[Any]:
        vals = set()
        for bundle in self._execute():
            block = ray_tpu.get(bundle.block_ref, timeout=600)
            col = BlockAccessor(block).to_numpy([column])[column]
            vals.update(np.asarray(col).tolist())
        return sorted(vals)

    # global aggregates (no shuffle: distributed partials + driver combine,
    # reference: Dataset.sum/min/max/mean/std)
    def aggregate(self, *aggs: agg_mod.AggregateFn) -> Dict[str, Any]:
        partial_refs = []
        for bundle in self._execute():
            ref = ray_tpu.remote(_partials_task).options(
                name="data:aggregate").remote(list(aggs), bundle.block_ref)
            partial_refs.append(ref)
        partials = ray_tpu.get(partial_refs, timeout=600)
        out = {}
        for i, agg in enumerate(aggs):
            parts = [p[i] for p in partials]
            out[agg.name] = agg.finalize(agg.combine(parts)) if parts \
                else None
        return out

    def sum(self, on: str):
        return self.aggregate(agg_mod.Sum(on))[f"sum({on})"]

    def min(self, on: str):
        return self.aggregate(agg_mod.Min(on))[f"min({on})"]

    def max(self, on: str):
        return self.aggregate(agg_mod.Max(on))[f"max({on})"]

    def mean(self, on: str):
        return self.aggregate(agg_mod.Mean(on))[f"mean({on})"]

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(agg_mod.Std(on, ddof))[f"std({on})"]

    # ------------------------------------------------------------------
    # iteration

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for bundle in self._execute():
            block = ray_tpu.get(bundle.block_ref, timeout=600)
            yield from BlockAccessor(block).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: Optional[str] = None,
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: Optional[int] = None) -> Iterator:
        # single implementation lives on DataIterator (reference shape:
        # Dataset.iter_batches delegates to Dataset.iterator())
        return self.iterator().iter_batches(
            batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed,
            prefetch_batches=prefetch_batches)

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         sharding=None, dtypes=None, drop_last: bool = True,
                         prefetch: int = 2, **kw) -> Iterator:
        """Iterate device-resident batches (dict of jax.Array), double
        buffered into HBM; with `sharding`, each batch is laid out across
        the mesh data axis; `dtypes` maps column -> target dtype cast
        before transfer (host-side, so e.g. bf16 halves the HBM traffic)."""
        host = self.iter_batches(batch_size=batch_size, batch_format="numpy",
                                 drop_last=drop_last, **kw)
        return iter_jax_batches(host, sharding=sharding, dtypes=dtypes,
                                prefetch=prefetch)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: str = "cpu",
                           drop_last: bool = False, **kw) -> Iterator:
        """Iterate dict-of-torch.Tensor batches (reference:
        data/iterator.py iter_torch_batches) — parity surface for torch
        consumers; jax consumers should prefer iter_jax_batches."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last, **kw):
            out = {}
            for k, v in batch.items():
                # blocks are zero-copy views over read-only shm mmaps:
                # torch tensors must own writable memory or in-place ops
                # would fault / corrupt the shared object
                if isinstance(v, np.ndarray) and not v.flags.writeable:
                    v = v.copy()
                t = torch.as_tensor(v)
                if dtypes:
                    want = dtypes.get(k) if isinstance(dtypes, dict) \
                        else dtypes
                    if want is not None:
                        t = t.to(want)
                if device != "cpu":
                    t = t.to(device)
                out[k] = t
            yield out

    # ------------------------------------------------------------------
    # split / writes

    def split(self, n: int, *, equal: bool = False,
              locality_hints=None) -> List["MaterializedDataset"]:
        mat = self.materialize()
        bundles = mat._bundles
        if equal:
            # exact equal-row splits: slice straddling blocks at the
            # per-split row boundaries (extra `total % n` rows dropped,
            # matching the reference's equal=True contract)
            total = sum(b.metadata.num_rows for b in bundles)
            per = total // n
            slicer = ray_tpu.remote(_slice_block_task)
            out: List[List[RefBundle]] = [[] for _ in range(n)]
            bi = 0          # current block index
            boff = 0        # rows of current block already consumed
            for j in range(n):
                need = per
                while need > 0 and bi < len(bundles):
                    b = bundles[bi]
                    avail = b.metadata.num_rows - boff
                    take = min(need, avail)
                    if take == avail and boff == 0:
                        out[j].append(b)  # whole block, no slice task
                    else:
                        ref = slicer.remote(b.block_ref, boff, take)
                        meta = BlockMetadata(num_rows=take, size_bytes=max(
                            1, b.metadata.size_bytes * take
                            // max(1, b.metadata.num_rows)))
                        out[j].append(RefBundle(ref, meta))
                    need -= take
                    boff += take
                    if boff >= b.metadata.num_rows:
                        bi += 1
                        boff = 0
            return [MaterializedDataset(s) for s in out]
        splits: List[List[RefBundle]] = [[] for _ in range(n)]
        # round-robin whole blocks (balanced by count)
        order = sorted(range(len(bundles)),
                       key=lambda i: -bundles[i].metadata.num_rows)
        sizes = [0] * n
        for i in order:
            j = sizes.index(min(sizes))
            splits[j].append(bundles[i])
            sizes[j] += bundles[i].metadata.num_rows
        return [MaterializedDataset(s) for s in splits]

    def split_at_indices(self, indices: List[int]
                         ) -> List["MaterializedDataset"]:
        return self._split_rows_at(self.take_all(), indices)

    def _write(self, path: str, fmt: str, **writer_args) -> List[str]:
        return [p for p, _ in self._write_parts(path, fmt, **writer_args)]

    def _write_parts(self, path: str, fmt: str, **writer_args):
        """Distributed write; one (file path, row count) pair per block."""
        def write(block: Block, _path=path, _fmt=fmt, _wa=writer_args):
            fname = write_block(block, _path, _fmt, **_wa)
            n = block.num_rows if hasattr(block, "num_rows") else len(block)
            return pa.table({"path": [fname], "rows": [n]})

        ds = self._with(L.MapBlocks(self._dag, write, name=f"Write({fmt})"))
        return [(r["path"], r["rows"]) for r in ds.take_all()]

    def write_parquet(self, path: str, **kw) -> List[str]:
        return self._write(path, "parquet", **kw)

    def write_csv(self, path: str, **kw) -> List[str]:
        return self._write(path, "csv", **kw)

    def write_json(self, path: str, **kw) -> List[str]:
        return self._write(path, "json", **kw)

    def write_numpy(self, path: str, **kw) -> List[str]:
        return self._write(path, "npy", **kw)

    def write_avro(self, path: str, **kw) -> List[str]:
        return self._write(path, "avro", **kw)

    def write_tfrecords(self, path: str, **kw) -> List[str]:
        """reference: dataset.py write_tfrecords (tf.train.Example files,
        written with the dependency-free codec in datasource.py)."""
        return self._write(path, "tfrecords", **kw)

    def write_webdataset(self, path: str, **kw) -> List[str]:
        """reference: dataset.py write_webdataset — one tar shard per
        block; rows become key-grouped members (`__key__` or the row
        index), columns encoded by extension (datasource.py
        _wds_encode_field); `encoder=` maps each row dict first."""
        return self._write(path, "tar", **kw)

    def write_delta(self, table_uri: str, *, mode: str = "append",
                    **kw) -> int:
        """Write this dataset as one Delta Lake commit: part files go
        through the normal distributed parquet write, then the driver
        commits them to `_delta_log` atomically (lake.commit_delta_write).
        mode='append'|'overwrite'.  Returns the committed version.
        reference surface: read_api.py's Delta integration is read-only
        (delta-sharing); the writer here makes the round trip testable
        and lets pod jobs publish snapshots consumers can time-travel."""
        from .lake import commit_delta_write

        parts = self._write_parts(table_uri, "parquet", **kw)
        return commit_delta_write(table_uri, parts, mode=mode)

    # -- additional consumption / conversion surface ----------------------

    def take_batch(self, batch_size: int = 20,
                   batch_format: Optional[str] = None) -> Dict[str, Any]:
        """reference: dataset.py take_batch — first `batch_size` rows as
        one batch."""
        from .context import DataContext

        fmt = batch_format or DataContext.get_current().default_batch_format
        for b in self.limit(batch_size).iter_batches(
                batch_size=batch_size, batch_format=fmt):
            return b
        raise ValueError("dataset is empty")

    def _split_rows_at(self, rows: List[Dict[str, Any]],
                       indices: List[int]) -> List["MaterializedDataset"]:
        bounds = [0] + list(indices) + [len(rows)]
        return [from_rows_materialized(rows[s:e])
                for s, e in zip(bounds[:-1], bounds[1:])]

    def train_test_split(self, test_size, *, shuffle: bool = False,
                         seed: Optional[int] = None):
        """reference: dataset.py train_test_split."""
        if isinstance(test_size, float):
            if not 0 < test_size < 1:
                raise ValueError(
                    f"test_size fraction must be in (0, 1), got {test_size}")
        elif not isinstance(test_size, int) or test_size <= 0:
            raise ValueError(
                f"test_size must be a positive int or a fraction in (0, 1), "
                f"got {test_size!r}")
        ds = self.random_shuffle(seed=seed) if shuffle else self
        rows = ds.take_all()  # one execution: count + split share it
        n_test = (int(len(rows) * test_size)
                  if isinstance(test_size, float) else test_size)
        if n_test > len(rows):
            raise ValueError(
                f"test_size {test_size} exceeds dataset size {len(rows)}")
        return tuple(self._split_rows_at(rows, [len(rows) - n_test]))

    def split_proportionately(self, proportions: List[float]
                              ) -> List["MaterializedDataset"]:
        """reference: dataset.py split_proportionately — len(p)+1 splits,
        the last taking the remainder."""
        if not proportions or sum(proportions) >= 1.0 \
                or any(p <= 0 for p in proportions):
            raise ValueError("proportions must be positive and sum to < 1")
        rows = self.take_all()  # one execution
        indices, acc = [], 0
        for p in proportions:
            acc += int(len(rows) * p)
            indices.append(acc)
        return self._split_rows_at(rows, indices)

    def randomize_block_order(self, *, seed: Optional[int] = None
                              ) -> "Dataset":
        """reference: dataset.py randomize_block_order — permute blocks
        without touching rows (cheap approximate shuffle; blocks stay in
        the object store, only their refs are reordered)."""
        import random as _random

        refs = [b.block_ref for b in self._execute()]
        rng = _random.Random(seed)
        rng.shuffle(refs)
        from . import from_arrow_refs

        return from_arrow_refs(refs)

    def size_bytes(self) -> int:
        """reference: dataset.py size_bytes."""
        total = 0
        for b in self._execute():
            n = b.metadata.size_bytes
            if not n:
                blk = ray_tpu.get(b.block_ref, timeout=600)
                n = BlockAccessor(blk).to_arrow().nbytes
            total += n
        return total

    def input_files(self) -> List[str]:
        """reference: dataset.py input_files."""
        files: List[str] = []
        for b in self._execute():
            for f in (b.metadata.input_files or []):
                if f not in files:
                    files.append(f)
        return files

    def to_arrow_refs(self) -> List[Any]:
        """reference: dataset.py to_arrow_refs — blocks ARE arrow tables."""
        return [b.block_ref for b in self._execute()]

    def to_pandas_refs(self) -> List[Any]:
        to_df = ray_tpu.remote(
            lambda b: BlockAccessor(b).to_arrow().to_pandas())
        return [to_df.remote(r) for r in self.to_arrow_refs()]

    def to_numpy_refs(self) -> List[Any]:
        to_np = ray_tpu.remote(lambda b: BlockAccessor(b).to_numpy())
        return [to_np.remote(r) for r in self.to_arrow_refs()]

    def to_torch(self, *, label_column: Optional[str] = None,
                 batch_size: int = 256, drop_last: bool = False):
        """reference: dataset.py to_torch — torch IterableDataset of
        (features, label) (or feature-dict) batches."""
        import torch

        outer = self

        class _TorchIterable(torch.utils.data.IterableDataset):
            def __iter__(self):
                for b in outer.iter_torch_batches(batch_size=batch_size,
                                                  drop_last=drop_last):
                    if label_column is not None:
                        label = b.pop(label_column)
                        feats = (next(iter(b.values()))
                                 if len(b) == 1 else b)
                        yield feats, label
                    else:
                        yield b

        return _TorchIterable()

    def iter_tf_batches(self, *, batch_size: Optional[int] = 256,
                        drop_last: bool = False, prefetch_batches: int = 2):
        """reference: iterator.py iter_tf_batches — dict of tf tensors."""
        import tensorflow as tf

        for b in self.iter_batches(batch_size=batch_size,
                                   batch_format="numpy",
                                   drop_last=drop_last,
                                   prefetch_batches=prefetch_batches):
            yield {k: tf.convert_to_tensor(v) for k, v in b.items()}

    def to_tf(self, feature_columns, label_columns, *,
              batch_size: int = 256, drop_last: bool = False):
        """reference: dataset.py to_tf — tf.data.Dataset of
        (features, labels) tensors."""
        import tensorflow as tf

        f_cols = ([feature_columns] if isinstance(feature_columns, str)
                  else list(feature_columns))
        l_cols = ([label_columns] if isinstance(label_columns, str)
                  else list(label_columns))

        def pick(b, cols):
            if len(cols) == 1:
                return b[cols[0]]
            return {c: b[c] for c in cols}

        first = self.take_batch(1, batch_format="numpy")

        def sig(cols):
            if len(cols) == 1:
                a = np.asarray(first[cols[0]])
                return tf.TensorSpec(shape=(None,) + a.shape[1:],
                                     dtype=tf.as_dtype(a.dtype))
            return {c: tf.TensorSpec(
                shape=(None,) + np.asarray(first[c]).shape[1:],
                dtype=tf.as_dtype(np.asarray(first[c]).dtype))
                for c in cols}

        def gen():
            for b in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
                yield pick(b, f_cols), pick(b, l_cols)

        return tf.data.Dataset.from_generator(
            gen, output_signature=(sig(f_cols), sig(l_cols)))

    def iterator(self) -> "Any":
        """reference: dataset.py iterator() -> DataIterator."""
        from .iterator import _DatasetIterator

        return _DatasetIterator(self)

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List[Any]:
        """reference: dataset.py streaming_split — n DataIterators served
        by one coordinator actor executing the stream once; the iterators
        serialize into Train worker tasks.  equal=True pre-splits into
        exact equal-row shards (SPMD workers must step in lockstep);
        equal=False streams blocks first-come-first-served."""
        from .iterator import _SplitCoordinator, _StreamSplitIterator

        coord = ray_tpu.remote(_SplitCoordinator).remote(self, n, equal)
        return [_StreamSplitIterator(coord, i) for i in range(n)]

    def __repr__(self):
        return f"Dataset(dag={self._dag!r})"


def _partials_task(aggs, block: Block):
    return [agg.partial(BlockAccessor(block).to_arrow()) for agg in aggs]


class MaterializedDataset(Dataset):
    """A Dataset whose blocks are already computed and held by refs
    (reference: MaterializedDataset)."""

    def __init__(self, bundles: List[RefBundle],
                 stats: Optional[str] = None):
        super().__init__(L.InputData(bundles))
        self._bundles = bundles
        self._last_stats = stats

    def num_blocks(self) -> int:
        return len(self._bundles)

    def count(self) -> int:  # no execution needed
        return sum(b.metadata.num_rows for b in self._bundles)

    def get_internal_block_refs(self):
        return [b.block_ref for b in self._bundles]


def from_rows_materialized(rows: List[Dict[str, Any]]) -> MaterializedDataset:
    from .block import rows_to_block

    block = rows_to_block(rows)
    ref = ray_tpu.put(block)
    meta = BlockAccessor(block).get_metadata()
    return MaterializedDataset([RefBundle(ref, meta)])
