"""Run a ray_tpu cluster on a Spark cluster (reference:
python/ray/util/spark/cluster_init.py — setup_ray_cluster starts the head
on the Spark driver and worker nodes inside a background Spark job whose
tasks each host one raylet; shutdown cancels the job).

The head (control plane + optional head raylet + client server) runs in
the driver process's machine as subprocesses.  Worker raylets are started
by a long-running background Spark job: one Spark task per worker node,
each task spawning `ray_tpu._private.node` pointed at the driver's
control address and blocking until the raylet exits (so cancelling the
Spark job group tears the workers down — the reference's
start_ray_node.py does the same).

pyspark is not a dependency: pass any session object with the duck-typed
`sparkContext.parallelize(n, n).mapPartitions(fn).collect()` +
`setJobGroup/cancelJobGroup` surface (tests use a local fake that runs
partitions in threads).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, Optional, Tuple

MAX_NUM_WORKER_NODES = -1

_active_cluster: Optional["RayClusterOnSpark"] = None
_setup_in_progress = False
_lock = threading.Lock()


def _free_port() -> int:
    from ray_tpu._private.protocol import free_port

    return free_port()


def _driver_host() -> str:
    # the address spark executors use to reach the driver's machine;
    # single-machine (and fake-spark test) setups resolve to loopback
    return os.environ.get("RAY_TPU_SPARK_DRIVER_HOST", "127.0.0.1")


def _make_worker_partition_fn(control_addr: str, resources_json: str,
                              collect_log_to_path: Optional[str]):
    """Build the function each Spark task runs: spawn one raylet against
    the head's control address and block until it exits (reference:
    start_ray_node.py — the task's lifetime IS the node's lifetime)."""

    def start_worker(iterator):
        import json
        import socket as _socket
        import subprocess as _sp
        import sys as _sys
        import tempfile
        import time as _time

        _ = list(iterator)  # consume the partition index
        cmd = [_sys.executable, "-m", "ray_tpu._private.node",
               "--control", control_addr,
               "--host", "127.0.0.1", "--port", "0"]
        if resources_json:
            cmd += ["--resources", resources_json]
        log_dir = collect_log_to_path or tempfile.mkdtemp(
            prefix="ray-tpu-spark-worker-")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(
            log_dir, f"raylet-{_socket.gethostname()}-{os.getpid()}.log")
        chost, cport = control_addr.rsplit(":", 1)
        with open(log_path, "ab") as log:
            proc = _sp.Popen(cmd, stdout=log, stderr=_sp.STDOUT,
                             start_new_session=True)
            try:
                # orphan prevention (reference: start_ray_node.py):
                # if the head's control plane stays unreachable the
                # cluster is gone — stop hosting the raylet.  This also
                # lets the whole job unwind when Spark can't interrupt
                # the task (our thread-based test fake can't).
                misses = 0
                while proc.poll() is None:
                    _time.sleep(1.0)
                    try:
                        s = _socket.create_connection(
                            (chost, int(cport)), timeout=2.0)
                        s.close()
                        misses = 0
                    except OSError:
                        misses += 1
                        if misses >= 3:
                            proc.terminate()
                            break
                proc.wait(timeout=15)
            finally:
                if proc.poll() is None:
                    proc.kill()
        return [json.dumps({"exit": proc.returncode, "log": log_path})]

    return start_worker


class RayClusterOnSpark:
    """Handle on a ray_tpu cluster hosted by a Spark application
    (reference: cluster_init.py:73 RayClusterOnSpark)."""

    def __init__(self, spark, address: str, client_address: str,
                 head_procs, job_group: str, job_thread: threading.Thread):
        self.spark = spark
        self.address = address
        self.client_address = client_address
        self._head_procs = head_procs
        self._job_group = job_group
        self._job_thread = job_thread
        self._shutdown = False

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        try:
            self.spark.sparkContext.cancelJobGroup(self._job_group)
        except Exception:
            pass
        # head down first: workers also self-terminate on control loss,
        # so the job thread unwinds even when cancel can't interrupt it
        for p in reversed(self._head_procs):  # raylet first, control last
            try:
                if p.poll() is None:
                    p.terminate()
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
            except Exception:
                pass
        self._job_thread.join(timeout=30.0)
        # only clear the env we exported: a failed setup (or a user
        # pointing at some other cluster) must not lose their address
        if os.environ.get("RAY_TPU_ADDRESS") == self.client_address \
                and self.client_address:
            os.environ.pop("RAY_TPU_ADDRESS", None)


def _spawn_head(host: str, num_cpus_head_node: Optional[float],
                temp_root: Optional[str]):
    """Start control (+ a head raylet when the head has resources)."""
    env = dict(os.environ)
    procs = []
    port = _free_port()
    log_dir = temp_root or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"ray-tpu-spark-{uuid.uuid4().hex[:8]}")
    os.makedirs(log_dir, exist_ok=True)
    with open(os.path.join(log_dir, "control.log"), "ab") as log:
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.control",
             "--host", host, "--port", str(port)],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            start_new_session=True))
    control_addr = f"{host}:{port}"
    _wait_control(control_addr)
    # head raylet: 0 CPUs by default, like the reference (head should not
    # run compute tasks unless asked)
    import json as _json

    head_res = {"CPU": float(num_cpus_head_node or 0)}
    with open(os.path.join(log_dir, "raylet-head.log"), "ab") as log:
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node",
             "--control", control_addr, "--host", host, "--port", "0",
             "--resources", _json.dumps(head_res)],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            start_new_session=True))
    return procs, control_addr, log_dir


def _wait_control(control_addr: str, timeout: float = 30.0):
    from ray_tpu._private.protocol import Client

    host, port = control_addr.rsplit(":", 1)
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            c = Client((host, int(port)), name="spark-head-probe",
                       connect_timeout=2.0)
            c.call("ping", timeout=5.0)
            c.close()
            return
        except Exception as e:
            last = e
            time.sleep(0.2)
    raise TimeoutError(f"control plane did not come up at {control_addr}: "
                       f"{last}")


def setup_ray_cluster(
    *,
    max_worker_nodes: int,
    min_worker_nodes: Optional[int] = None,
    num_cpus_worker_node: Optional[float] = None,
    num_cpus_head_node: Optional[float] = None,
    num_tpus_worker_node: Optional[float] = None,
    head_node_options: Optional[Dict] = None,
    worker_node_options: Optional[Dict] = None,
    ray_temp_root_dir: Optional[str] = None,
    strict_mode: bool = False,
    collect_log_to_path: Optional[str] = None,
    spark=None,
) -> Tuple[str, str]:
    """Start a ray_tpu cluster on the Spark application (reference:
    cluster_init.py:1190).  Returns (cluster_address, client_address);
    also exports RAY_TPU_ADDRESS so a bare `ray_tpu.init()` connects.

    num_tpus_worker_node is the TPU-native analog of the reference's
    num_gpus_worker_node — it becomes each worker raylet's TPU resource.
    """
    global _active_cluster, _setup_in_progress
    with _lock:
        if _setup_in_progress or (
                _active_cluster is not None
                and not _active_cluster._shutdown):
            raise RuntimeError(
                "an active ray_tpu-on-spark cluster (or setup in "
                "progress) exists; call shutdown_ray_cluster() first")
        _setup_in_progress = True
    try:
        return _setup_ray_cluster_locked(
            max_worker_nodes=max_worker_nodes,
            min_worker_nodes=min_worker_nodes,
            num_cpus_worker_node=num_cpus_worker_node,
            num_cpus_head_node=num_cpus_head_node,
            num_tpus_worker_node=num_tpus_worker_node,
            head_node_options=head_node_options,
            worker_node_options=worker_node_options,
            ray_temp_root_dir=ray_temp_root_dir,
            strict_mode=strict_mode,
            collect_log_to_path=collect_log_to_path,
            spark=spark)
    finally:
        with _lock:
            _setup_in_progress = False


def _setup_ray_cluster_locked(
    *,
    max_worker_nodes: int,
    min_worker_nodes: Optional[int],
    num_cpus_worker_node: Optional[float],
    num_cpus_head_node: Optional[float],
    num_tpus_worker_node: Optional[float],
    head_node_options: Optional[Dict],
    worker_node_options: Optional[Dict],
    ray_temp_root_dir: Optional[str],
    strict_mode: bool,
    collect_log_to_path: Optional[str],
    spark,
) -> Tuple[str, str]:
    global _active_cluster
    if spark is None:
        try:
            from pyspark.sql import SparkSession

            spark = SparkSession.getActiveSession()
        except ImportError as e:
            raise ImportError(
                "setup_ray_cluster needs a Spark session: install pyspark "
                "or pass spark=<session-like object>") from e
        if spark is None:
            raise RuntimeError("no active SparkSession found")

    n_workers = max_worker_nodes
    if n_workers == MAX_NUM_WORKER_NODES:
        n_workers = int(spark.sparkContext.defaultParallelism)
    if n_workers <= 0:
        raise ValueError(f"max_worker_nodes must be positive or "
                         f"MAX_NUM_WORKER_NODES, got {max_worker_nodes}")
    if min_worker_nodes is not None and not (
            0 <= min_worker_nodes <= n_workers):
        raise ValueError("min_worker_nodes must be in [0, max_worker_nodes]")

    host = _driver_host()
    head_procs, control_addr, log_dir = _spawn_head(
        host, num_cpus_head_node, ray_temp_root_dir)

    import json as _json

    res = {}
    if num_cpus_worker_node is not None:
        res["CPU"] = float(num_cpus_worker_node)
    if num_tpus_worker_node is not None:
        res["TPU"] = float(num_tpus_worker_node)
    resources_json = _json.dumps(res) if res else ""

    job_group = f"ray-tpu-cluster-{uuid.uuid4().hex[:12]}"
    partition_fn = _make_worker_partition_fn(
        control_addr, resources_json, collect_log_to_path)

    def run_job():
        sc = spark.sparkContext
        try:
            sc.setJobGroup(job_group,
                           "ray_tpu worker nodes (long-running)", True)
            sc.parallelize(list(range(n_workers)), n_workers) \
                .mapPartitions(partition_fn).collect()
        except Exception:
            pass  # cancelled at shutdown — expected

    t = threading.Thread(target=run_job, daemon=True,
                         name="ray-tpu-spark-job")
    t.start()

    # wait for the workers to register (strict_mode: all of them;
    # otherwise min_worker_nodes — 0 means don't wait — defaulting to 1)
    want = n_workers if strict_mode else (
        min_worker_nodes if min_worker_nodes is not None else 1)
    try:
        if want > 0:
            _wait_workers(control_addr, want)

        client_port = _free_port()
        from ray_tpu.util.client import ClientServer

        chost, cport = control_addr.rsplit(":", 1)
        srv = ClientServer((chost, int(cport)), host=host, port=client_port)
        srv.start()
    except BaseException:
        # failed startup must not orphan the head daemons or leave the
        # background job hosting raylets (they self-terminate once the
        # control plane is gone)
        RayClusterOnSpark(spark, control_addr, "", head_procs,
                          job_group, t).shutdown()
        raise
    client_address = f"ray-tpu://{host}:{client_port}"

    cluster = RayClusterOnSpark(spark, control_addr, client_address,
                                head_procs, job_group, t)
    cluster._client_server = srv
    with _lock:
        _active_cluster = cluster
    os.environ["RAY_TPU_ADDRESS"] = client_address
    return control_addr, client_address


def _wait_workers(control_addr: str, want: int, timeout: float = 60.0):
    from ray_tpu._private.protocol import Client

    host, port = control_addr.rsplit(":", 1)
    deadline = time.monotonic() + timeout
    c = Client((host, int(port)), name="spark-worker-wait")
    try:
        while time.monotonic() < deadline:
            nodes = c.call("get_nodes", timeout=10.0)
            # head raylet has 0 CPUs; count the worker raylets
            alive = [n for n in nodes if n["state"] == "ALIVE"]
            if len(alive) >= want + 1:  # +1: head raylet
                return
            time.sleep(0.3)
    finally:
        c.close()
    raise TimeoutError(
        f"{want} spark worker node(s) did not register within {timeout}s")


def setup_global_ray_cluster(*, max_worker_nodes: int,
                             is_blocking: bool = True, **kwargs):
    """Shared-mode cluster (reference: cluster_init.py:1357): same as
    setup_ray_cluster but intended to outlive the calling notebook; with
    is_blocking the call parks until interrupted."""
    addrs = setup_ray_cluster(max_worker_nodes=max_worker_nodes, **kwargs)
    if is_blocking:
        try:
            while _active_cluster is not None and not _active_cluster._shutdown:
                time.sleep(1.0)
        except KeyboardInterrupt:
            shutdown_ray_cluster()
    return addrs


def shutdown_ray_cluster() -> None:
    """Tear down the active cluster (reference: cluster_init.py:1659)."""
    global _active_cluster
    with _lock:
        cluster = _active_cluster
        _active_cluster = None
    if cluster is None:
        raise RuntimeError("no active ray_tpu-on-spark cluster")
    srv = getattr(cluster, "_client_server", None)
    if srv is not None:
        try:
            srv.stop()
        except Exception:
            pass
    cluster.shutdown()
