"""ray_tpu.util: scheduling and cluster utilities.

Mirrors the reference's `ray.util` namespace (reference: python/ray/util/):
placement groups (util/placement_group.py:41,145), scheduling strategies
(util/scheduling_strategies.py), ActorPool (util/actor_pool.py), Queue
(util/queue.py).
"""

from .actor_pool import ActorPool
from .placement_group import (PlacementGroup, get_placement_group,
                              placement_group, placement_group_table,
                              remove_placement_group)
from .queue import Empty, Full, Queue
from .scheduling_strategies import (NodeAffinitySchedulingStrategy,
                                    PlacementGroupSchedulingStrategy)

__all__ = [
    "ActorPool",
    "Empty",
    "Full",
    "NodeAffinitySchedulingStrategy",
    "PlacementGroup",
    "PlacementGroupSchedulingStrategy",
    "Queue",
    "get_placement_group",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
]
