"""Distributed FIFO queue backed by a single queue actor.

Mirrors the reference's ray.util.queue.Queue (reference:
python/ray/util/queue.py): put/get with block+timeout, put/get_nowait,
batch variants, qsize/empty/full, shutdown.
"""

from __future__ import annotations

import queue as _stdlib_queue
import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._q = _stdlib_queue.Queue(maxsize=maxsize)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()

    def put(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except _stdlib_queue.Full:
            return False

    def put_batch(self, items: List[Any]) -> bool:
        """All-or-nothing: insert only if every item fits (matching the
        reference's put_nowait_batch, which raises Full without inserting)."""
        if self._q.maxsize and self._q.qsize() + len(items) > self._q.maxsize:
            return False
        for it in items:
            self._q.put_nowait(it)
        return True

    def get(self):
        try:
            return True, self._q.get_nowait()
        except _stdlib_queue.Empty:
            return False, None

    def get_batch(self, num_items: int):
        """All-or-nothing: dequeue only if num_items are present (matching
        the reference's get_nowait_batch, which raises Empty without
        removing anything)."""
        if self._q.qsize() < num_items:
            return None
        return [self._q.get_nowait() for _ in range(num_items)]


class Queue:
    """Actor-backed queue usable from any worker or driver."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        self.actor = ray_tpu.remote(_QueueActor).options(**opts).remote(maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        if not ray_tpu.get(self.actor.put.remote(item)):
            if not block:
                raise Full
            # ship the payload once: poll full-ness with a payload-free
            # probe, resend only when space appeared
            while True:
                if deadline is not None and time.monotonic() > deadline:
                    raise Full
                time.sleep(0.01)
                if not ray_tpu.get(self.actor.full.remote()):
                    if ray_tpu.get(self.actor.put.remote(item)):
                        return
        else:
            return

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self.actor.put_batch.remote(list(items))):
            raise Full(f"batch of {len(items)} does not fit")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self.actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() > deadline:
                raise Empty
            time.sleep(0.01)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        items = ray_tpu.get(self.actor.get_batch.remote(num_items))
        if items is None:
            raise Empty(f"queue has fewer than {num_items} items")
        return items

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
