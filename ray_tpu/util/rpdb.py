"""Distributed pdb: breakpoints inside remote tasks/actors.

Reference: python/ray/util/rpdb.py + the `ray debug` CLI — a task calls
``set_trace()``, which opens a TCP socket, registers the active
breakpoint in the control KV, and serves a pdb session over the socket;
``ray-tpu debug`` lists active breakpoints and attaches the terminal.
"""

from __future__ import annotations

import json
import os
import pdb
import socket
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

KV_NS = "_breakpoints"


def set_trace() -> None:
    """Block until a debugger client attaches, then drop into pdb in the
    caller's frame, with I/O over the socket."""
    from ray_tpu._private.core import current_core

    core = current_core()
    # bind the interface this worker serves RPC on, not loopback — the
    # attaching CLI may run on another node (reference rpdb binds the
    # node ip)
    host = core.addr[0] if getattr(core, "addr", None) else "127.0.0.1"
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind((host, 0))
    srv.listen(1)
    bp_id = f"bp-{uuid.uuid4().hex[:10]}"
    info = {
        "id": bp_id,
        "addr": list(srv.getsockname()),
        "pid": os.getpid(),
        "worker_id": core.worker_id,
        "ts": time.time(),
    }
    core.control.call("kv_put", {
        "ns": KV_NS, "key": bp_id,
        "val": json.dumps(info).encode(), "overwrite": True,
    }, timeout=30.0)
    try:
        conn, _ = srv.accept()
    finally:
        try:
            core.control.call("kv_del", {"ns": KV_NS, "key": bp_id},
                              timeout=10.0)
        except Exception:
            pass
        srv.close()
    fh = conn.makefile("rw", buffering=1)
    debugger = pdb.Pdb(stdin=fh, stdout=fh)
    debugger.use_rawinput = False
    debugger.set_trace(sys._getframe().f_back)


def list_breakpoints(control) -> List[Dict[str, Any]]:
    out = []
    try:
        keys = control.call("kv_keys", {"ns": KV_NS, "prefix": ""},
                            timeout=10.0)
        for k in keys:
            raw = control.call("kv_get", {"ns": KV_NS, "key": k},
                               timeout=10.0)
            if raw:
                out.append(json.loads(
                    raw.decode() if isinstance(raw, bytes) else raw))
    except Exception:
        pass
    return sorted(out, key=lambda b: b.get("ts", 0))


def attach(addr, stdin=None, stdout=None) -> None:
    """Bridge the local terminal to a breakpoint's pdb socket."""
    import threading

    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout
    conn = socket.create_connection(tuple(addr), timeout=10)

    def pump_out():
        while True:
            data = conn.recv(4096)
            if not data:
                return
            stdout.write(data.decode(errors="replace"))
            stdout.flush()

    t = threading.Thread(target=pump_out, daemon=True)
    t.start()
    try:
        for line in stdin:
            conn.sendall(line.encode())
            if line.strip() in ("c", "continue", "q", "quit", "exit"):
                break
    finally:
        try:
            conn.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        t.join(timeout=2.0)
        conn.close()
