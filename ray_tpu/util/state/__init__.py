"""State observability API (reference: python/ray/util/state)."""

from .api import (StateApiClient, available_resources, cluster_resources,
                  get_actor, get_log, get_node, get_placement_group,
                  get_task,
                  list_actors, list_cluster_events, list_jobs, list_logs,
                  list_nodes, list_objects,
                  list_placement_groups, list_tasks, list_workers,
                  summarize_actors, summarize_objects, summarize_tasks,
                  timeline)

__all__ = [
    "StateApiClient", "available_resources", "cluster_resources",
    "get_actor", "get_log", "get_node", "get_placement_group", "get_task",
    "list_actors", "list_cluster_events", "list_jobs", "list_logs",
    "list_nodes", "list_objects",
    "list_placement_groups", "list_tasks", "list_workers",
    "summarize_actors", "summarize_objects", "summarize_tasks", "timeline",
]
