"""Cluster state observability API.

Analog of `ray.util.state` (reference: python/ray/util/state/api.py): typed
`list_*` / `get_*` / `summarize_*` queries over live cluster state.  Sources
of truth mirror the reference's: the control plane (GCS equivalent — nodes,
actors, placement groups, jobs, task events from the GcsTaskManager analog)
plus per-node raylets (workers, object-store stats), aggregated client-side
the way the reference's StateDataSourceClient/state_aggregator does
(reference: python/ray/util/state/state_manager.py,
python/ray/dashboard/state_aggregator.py).

All functions accept an optional ``address`` ("host:port" of the control
plane) so they work from an unconnected process (the CLI); inside a driver
they default to the current connection.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "list_nodes", "list_actors", "list_placement_groups", "list_jobs",
    "list_tasks", "list_objects", "list_workers",
    "get_node", "get_actor", "get_task", "get_placement_group",
    "summarize_tasks", "summarize_actors", "summarize_objects",
    "cluster_resources", "available_resources", "timeline", "StateApiClient",
    "control_stats", "device_stats",
]


def _parse_addr(address: str) -> Tuple[str, int]:
    host, port = address.rsplit(":", 1)
    return host, int(port)


class StateApiClient:
    """Owns the control-plane connection used by the free functions.

    With no address, piggybacks on the current driver's connection; with an
    address, opens a short-lived client (closed via ``close()``).
    """

    def __init__(self, address: Optional[str] = None):
        self._own = None
        if address is None:
            from ray_tpu._private.api import current_core

            core = current_core()
            if core is None:
                raise RuntimeError(
                    "not connected: call ray_tpu.init() or pass address=")
            self._control = core.control
        else:
            from ray_tpu._private.protocol import Client

            self._own = Client(_parse_addr(address), name="state-api")
            self._control = self._own

    def close(self):
        if self._own is not None:
            self._own.close()

    # -- raw sources -------------------------------------------------------

    def state_dump(self) -> Dict[str, Any]:
        return self._control.call("state_dump", {}, timeout=10.0)

    def task_events(self, filters=None, limit=10000) -> Dict[str, Any]:
        return self._control.call(
            "list_task_events", {"filters": filters, "limit": limit},
            timeout=10.0)

    def profile_events(self, limit=50000) -> List[Dict[str, Any]]:
        return self._control.call("list_profile_events", {"limit": limit},
                                  timeout=10.0)

    def control_stats(self) -> Dict[str, Any]:
        """Control-plane flight-recorder snapshot: per-handler RPC stats,
        loop lag, KV namespace counters, pubsub fan-out, event-queue
        depth (the `ray-tpu control-stats` CLI renders this)."""
        return self._control.call("control_stats", {}, timeout=10.0)

    def device_stats(self) -> Dict[str, Any]:
        """Cluster-wide device runtime observability: merged XLA
        compilation ledgers (compile/recompile counts, cause diffs,
        storm advisories) + device-memory censuses (the `ray-tpu
        device-stats` CLI and `GET /api/device/stats` render this)."""
        from ray_tpu.telemetry.device import collect_device_stats

        return collect_device_stats(self._control)

    def per_node(self, method: str, payload=None) -> Dict[str, Any]:
        """Fan a query out to every alive raylet (node_id -> reply)."""
        from ray_tpu._private.protocol import Client

        out = {}
        for n in self._control.call("get_nodes", {}, timeout=10.0):
            if n["state"] != "ALIVE":
                continue
            try:
                c = Client(tuple(n["addr"]), name="state-api-node")
                try:
                    out[n["node_id"]] = c.call(method, payload or {},
                                               timeout=10.0)
                finally:
                    c.close()
            except Exception as e:
                out[n["node_id"]] = {"error": str(e)}
        return out


def _client(address: Optional[str]) -> StateApiClient:
    return StateApiClient(address)


def _run(address, fn):
    c = _client(address)
    try:
        return fn(c)
    finally:
        c.close()


# -- list_* -----------------------------------------------------------------

def list_nodes(address: Optional[str] = None, *, filters=None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    def go(c):
        nodes = c.state_dump()["nodes"]
        return _filter(nodes, filters)[:limit]
    return _run(address, go)


def list_actors(address: Optional[str] = None, *, filters=None,
                limit: int = 1000) -> List[Dict[str, Any]]:
    def go(c):
        return _filter(c.state_dump()["actors"], filters)[:limit]
    return _run(address, go)


def list_placement_groups(address: Optional[str] = None, *, filters=None,
                          limit: int = 1000) -> List[Dict[str, Any]]:
    def go(c):
        return _filter(c.state_dump()["pgs"], filters)[:limit]
    return _run(address, go)


def list_jobs(address: Optional[str] = None, *, filters=None,
              limit: int = 1000) -> List[Dict[str, Any]]:
    def go(c):
        jobs = [dict(v, job_id=k) for k, v in c.state_dump()["jobs"].items()]
        return _filter(jobs, filters)[:limit]
    return _run(address, go)


def list_cluster_events(address: Optional[str] = None, *,
                        severity: Optional[str] = None,
                        source: Optional[str] = None,
                        entity_id: Optional[str] = None,
                        after_seq: int = 0,
                        limit: int = 1000) -> List[Dict[str, Any]]:
    """Structured cluster events (reference: `ray list cluster-events`,
    src/ray/util/event.h): node/actor/PG/job lifecycle transitions with
    severity, distinct from free-text logs."""
    def go(c):
        return c._control.call("list_events", {
            "severity": severity, "source": source,
            "entity_id": entity_id, "after_seq": after_seq,
            "limit": limit}, timeout=10.0)
    return _run(address, go)


def list_tasks(address: Optional[str] = None, *, filters=None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    def go(c):
        return c.task_events(filters=filters, limit=limit)["records"]
    return _run(address, go)


def list_workers(address: Optional[str] = None, *, filters=None,
                 limit: int = 10000) -> List[Dict[str, Any]]:
    def go(c):
        out = []
        for node_id, workers in c.per_node("list_workers").items():
            if isinstance(workers, list):
                out.extend(workers)
        return _filter(out, filters)[:limit]
    return _run(address, go)


def list_objects(address: Optional[str] = None, *, filters=None,
                 limit: int = 10000) -> List[Dict[str, Any]]:
    """Objects in per-node shared-memory stores (reference: `ray memory` /
    list_objects reads plasma store state via raylets)."""
    def go(c):
        out = []
        for node_id, stats in c.per_node("store_stats",
                                         {"detail": True}).items():
            for o in stats.get("objects", []):
                out.append(dict(o, node_id=node_id))
        return _filter(out, filters)[:limit]
    return _run(address, go)


def list_logs(address: Optional[str] = None, *, node_id: Optional[str] = None
              ) -> Dict[str, List[Dict[str, Any]]]:
    """node_id -> [{name, size_bytes}, ...] (reference: `ray logs` CLI
    listing via the dashboard log module)."""
    def go(c):
        out = {}
        for nid, reply in c.per_node("list_logs").items():
            if node_id is not None and nid != node_id:
                continue
            if isinstance(reply, dict):
                out[nid] = reply.get("logs", [])
        return out
    return _run(address, go)


def get_log(name: str, address: Optional[str] = None, *,
            node_id: Optional[str] = None,
            tail_bytes: int = 64 * 1024) -> Dict[str, Optional[str]]:
    """node_id -> tail of the named log file (None if absent there)."""
    def go(c):
        out = {}
        for nid, text in c.per_node(
                "read_log", {"name": name,
                             "tail_bytes": tail_bytes}).items():
            if node_id is not None and nid != node_id:
                continue
            out[nid] = text
        return out
    return _run(address, go)


def control_stats(address: Optional[str] = None,
                  *, per_node: bool = False) -> Dict[str, Any]:
    """Control-plane flight recorder snapshot; with ``per_node=True``
    also fans ``rpc_stats`` + ``loop_stats`` out to every alive raylet
    so one call covers every control-plane server in the cluster."""
    def go(c):
        out = {"control": c.control_stats()}
        if per_node:
            handlers = c.per_node("rpc_stats")
            loops = c.per_node("loop_stats")
            out["raylets"] = {
                nid: (reply if isinstance(reply, dict) and "error" in reply
                      else {"handlers": reply, "loop": loops.get(nid)})
                for nid, reply in handlers.items()}
        return out
    return _run(address, go)


def device_stats(address: Optional[str] = None) -> Dict[str, Any]:
    """Cluster-wide compilation-ledger + memory-census merge (see
    telemetry/device.py)."""
    return _run(address, lambda c: c.device_stats())


# -- get_* ------------------------------------------------------------------

def get_node(node_id: str, address: Optional[str] = None):
    return _first(list_nodes(address, filters={"node_id": node_id}))


def get_actor(actor_id: str, address: Optional[str] = None):
    return _first(list_actors(address, filters={"actor_id": actor_id}))


def get_task(task_id: str, address: Optional[str] = None):
    return _first(list_tasks(address, filters={"task_id": task_id}))


def get_placement_group(pg_id: str, address: Optional[str] = None):
    return _first(list_placement_groups(address, filters={"pg_id": pg_id}))


# -- summaries (reference: `ray summary tasks|actors|objects`) --------------

def summarize_tasks(address: Optional[str] = None) -> Dict[str, Any]:
    recs = list_tasks(address, limit=100000)
    by_func: Dict[str, Dict[str, int]] = {}
    for r in recs:
        d = by_func.setdefault(r.get("name", "?"), {})
        d[r.get("state", "?")] = d.get(r.get("state", "?"), 0) + 1
    return {"summary": by_func, "total": len(recs)}


def summarize_actors(address: Optional[str] = None) -> Dict[str, Any]:
    recs = list_actors(address, limit=100000)
    by_class: Dict[str, Dict[str, int]] = {}
    for r in recs:
        d = by_class.setdefault(r.get("class_name", "?"), {})
        d[r.get("state", "?")] = d.get(r.get("state", "?"), 0) + 1
    return {"summary": by_class, "total": len(recs)}


def summarize_objects(address: Optional[str] = None) -> Dict[str, Any]:
    def go(c):
        total_objs, total_bytes, per_node = 0, 0, {}
        for node_id, stats in c.per_node("store_stats").items():
            if "error" in stats:
                continue
            total_objs += stats.get("num_objects", 0)
            total_bytes += stats.get("bytes", 0)
            per_node[node_id] = stats
        return {"total_objects": total_objs, "total_bytes": total_bytes,
                "per_node": per_node}
    return _run(address, go)


def cluster_resources(address: Optional[str] = None) -> Dict[str, float]:
    def go(c):
        return c._control.call("cluster_resources", {}, timeout=10.0)["total"]
    return _run(address, go)


def available_resources(address: Optional[str] = None) -> Dict[str, float]:
    def go(c):
        return c._control.call("cluster_resources", {},
                               timeout=10.0)["available"]
    return _run(address, go)


# -- timeline (reference: `ray timeline` -> chrome://tracing) ---------------

def timeline(filename: Optional[str] = None,
             address: Optional[str] = None) -> Optional[str]:
    """Export task events as a Chrome trace (load in chrome://tracing or
    Perfetto).  Tasks become complete ('X') events on a (node, worker) row;
    profile spans nest beneath them."""
    def go(c):
        events = []
        recs = c.task_events(limit=100000)["records"]
        for r in recs:
            ts = r.get("state_ts", {})
            start = ts.get("RUNNING")
            end = ts.get("FINISHED") or ts.get("FAILED")
            if start is None:
                continue
            end = end if end is not None else time.time()
            events.append({
                "name": r.get("name", "?"),
                "cat": "task",
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(end - start, 1e-6) * 1e6,
                "pid": r.get("node_id", "?")[:12],
                "tid": r.get("worker_id", "?")[:12],
                "args": {k: v for k, v in r.items() if k != "state_ts"},
            })
        for p in c.profile_events(limit=100000):
            events.append({
                "name": p.get("event_name", "?"),
                "cat": "profile",
                "ph": "X",
                "ts": p["start_ts"] * 1e6,
                "dur": max(p["end_ts"] - p["start_ts"], 1e-6) * 1e6,
                "pid": p.get("node_id", "?")[:12],
                "tid": p.get("worker_id", "?")[:12],
            })
        return events
    events = _run(address, go)
    if filename is None:
        return json.dumps(events)
    with open(filename, "w") as f:
        json.dump(events, f)
    return None


# -- helpers ----------------------------------------------------------------

def _filter(rows: List[Dict[str, Any]], filters) -> List[Dict[str, Any]]:
    if not filters:
        return rows
    items = filters.items() if isinstance(filters, dict) else filters
    return [r for r in rows if all(r.get(k) == v for k, v in items)]


def _first(rows):
    return rows[0] if rows else None
