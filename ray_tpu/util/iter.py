"""Parallel iterators over actor shards.

Analog of the reference's ray.util.iter (reference: python/ray/util/iter.py
— from_items/from_range/from_iterators -> ParallelIterator over
ParallelIteratorWorker actors, with for_each/filter/batch/gather_sync/
gather_async/union and local shard access).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, TypeVar

import ray_tpu

T = TypeVar("T")
U = TypeVar("U")


@ray_tpu.remote
class ParallelIteratorWorker:
    """Hosts one shard: a base iterable + a chain of transforms."""

    def __init__(self, items, repeat: bool = False):
        self._base = items
        self._repeat = repeat
        self._ops: List = []
        self._it = None

    def apply_op(self, kind: str, fn):
        self._ops.append((kind, fn))
        self._it = None
        return True

    def _build(self):
        if callable(self._base):
            it = self._base()
        else:
            it = iter(self._base)
        if self._repeat:
            base = self._base

            def forever():
                while True:
                    src = base() if callable(base) else iter(list(base))
                    yielded = False
                    for x in src:
                        yielded = True
                        yield x
                    if not yielded:
                        return

            it = forever()
        for kind, fn in self._ops:
            if kind == "for_each":
                it = map(fn, it)
            elif kind == "filter":
                it = filter(fn, it)
            elif kind == "batch":
                it = _batched(it, fn)
            elif kind == "flatten":
                it = itertools.chain.from_iterable(it)
        return it

    def next_batch(self, n: int = 1):
        """Pull up to n items; [] signals exhaustion."""
        if self._it is None:
            self._it = self._build()
        out = list(itertools.islice(self._it, n))
        return out


def _batched(it, n):
    while True:
        chunk = list(itertools.islice(it, n))
        if not chunk:
            return
        yield chunk


class LocalIterator:
    """Driver-side view of gathered results."""

    def __init__(self, gen_factory: Callable[[], Iterable]):
        self._factory = gen_factory

    def __iter__(self):
        return iter(self._factory())

    def take(self, n: int) -> List[Any]:
        return list(itertools.islice(iter(self), n))


class ParallelIterator:
    def __init__(self, actors: List):
        self._actors = actors

    @property
    def num_shards(self) -> int:
        return len(self._actors)

    # -- transforms (lazy, applied on the shard actors) --------------------

    def _apply(self, kind: str, fn) -> "ParallelIterator":
        ray_tpu.get([a.apply_op.remote(kind, fn) for a in self._actors])
        return self

    def for_each(self, fn: Callable[[T], U]) -> "ParallelIterator":
        return self._apply("for_each", fn)

    def filter(self, fn: Callable[[T], bool]) -> "ParallelIterator":
        return self._apply("filter", fn)

    def batch(self, n: int) -> "ParallelIterator":
        return self._apply("batch", n)

    def flatten(self) -> "ParallelIterator":
        return self._apply("flatten", None)

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        return ParallelIterator(self._actors + other._actors)

    # -- consumption -------------------------------------------------------

    def gather_sync(self, batch: int = 16) -> LocalIterator:
        """Round-robin over shards, in order."""
        actors = self._actors

        def gen():
            live = list(actors)
            while live:
                done = []
                for a in live:
                    chunk = ray_tpu.get(a.next_batch.remote(batch))
                    if not chunk:
                        done.append(a)
                    else:
                        yield from chunk
                live = [a for a in live if a not in done]

        return LocalIterator(gen)

    def gather_async(self, batch: int = 16) -> LocalIterator:
        """Yield from whichever shard finishes first."""
        actors = self._actors

        def gen():
            inflight = {a.next_batch.remote(batch): a for a in actors}
            while inflight:
                ready, _ = ray_tpu.wait(list(inflight), num_returns=1)
                a = inflight.pop(ready[0])
                chunk = ray_tpu.get(ready[0])
                if chunk:
                    inflight[a.next_batch.remote(batch)] = a
                    yield from chunk

        return LocalIterator(gen)

    def take(self, n: int) -> List[Any]:
        return self.gather_sync().take(n)


# -- constructors (reference: from_items :1078, from_range, from_iterators) -

def from_items(items: List[T], num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    shards = [items[i::num_shards] for i in range(num_shards)]
    return ParallelIterator([
        ParallelIteratorWorker.remote(s, repeat) for s in shards])


def from_range(n: int, num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    return from_items(list(range(n)), num_shards, repeat)


def from_iterators(generators: List[Callable[[], Iterable]],
                   repeat: bool = False) -> ParallelIterator:
    return ParallelIterator([
        ParallelIteratorWorker.remote(g, repeat) for g in generators])
