"""Task/actor span tracing with W3C traceparent propagation.

Reference: python/ray/util/tracing/tracing_helper.py — opt-in tracing
that wraps task/actor submission (PRODUCER span) and execution (CONSUMER
span) and propagates the span context inside the task spec, so a
distributed trace stitches across processes.

The recorder is native (this image ships only the opentelemetry API
package, not the SDK): spans carry OTel-shaped fields (trace_id,
span_id, parent_id, kind, ns timestamps) and context crosses processes
as a standard ``traceparent`` header, so exported traces drop into any
OTel pipeline.  Enable with
``ray_tpu.init(_tracing_startup_hook="module:function")`` — the hook
runs in the driver AND every worker (its name travels through the
control KV) and must call ``configure(sink)`` (or use the built-in
``setup_file_exporter`` hook, which appends finished spans as JSON
lines to the configured ``trace_file``).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

KV_NS = "_tracing"

_enabled = False
_sink: Optional[Callable[[Dict[str, Any]], None]] = None
# contextvar, not thread-local: spans opened inside asyncio Tasks must
# attribute per-Task even though all coroutines share the loop thread
_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None)


def is_enabled() -> bool:
    return _enabled


def configure(sink: Callable[[Dict[str, Any]], None]) -> None:
    """Install a span sink (called once per finished span) and enable."""
    global _enabled, _sink
    _sink = sink
    _enabled = True


def enable() -> None:
    global _enabled
    _enabled = True


def _new_id(nbytes: int) -> int:
    return int.from_bytes(os.urandom(nbytes), "big") or 1


def _current() -> Optional[Dict[str, int]]:
    return _ctx.get()


def inject_context() -> Optional[Dict[str, str]]:
    """Current span context as a W3C traceparent carrier."""
    ctx = _current()
    if not _enabled or ctx is None:
        return None
    return {"traceparent":
            f"00-{ctx['trace_id']:032x}-{ctx['span_id']:016x}-01"}


def _extract(carrier: Optional[Dict[str, str]]
             ) -> Optional[Dict[str, int]]:
    tp = (carrier or {}).get("traceparent", "")
    parts = tp.split("-")
    if len(parts) != 4:
        return None
    try:
        return {"trace_id": int(parts[1], 16), "span_id": int(parts[2], 16)}
    except ValueError:
        return None


@contextlib.contextmanager
def _span(name: str, kind: str,
          parent: Optional[Dict[str, int]], **attrs):
    if not _enabled:
        yield None
        return
    parent = parent if parent is not None else _current()
    span = {
        "name": name,
        "trace_id": parent["trace_id"] if parent else _new_id(16),
        "span_id": _new_id(8),
        "parent_id": parent["span_id"] if parent else None,
        "kind": kind,
        "start_ns": time.time_ns(),
        "attributes": {k: v for k, v in attrs.items() if v is not None},
    }
    token = _ctx.set({"trace_id": span["trace_id"],
                      "span_id": span["span_id"]})
    try:
        yield span
    finally:
        _ctx.reset(token)
        span["end_ns"] = time.time_ns()
        record = dict(span)
        record["trace_id"] = f"{span['trace_id']:032x}"
        record["span_id"] = f"{span['span_id']:016x}"
        if span["parent_id"] is not None:
            record["parent_id"] = f"{span['parent_id']:016x}"
        if _sink is not None:
            try:
                _sink(record)
            except Exception:
                logger.exception("span sink failed")


def span(name: str, kind: str = "INTERNAL", **attrs):
    """Public INTERNAL span, auto-parented to the current context — a
    span opened inside task execution links to the submitting task's
    trace through the propagated traceparent (the collective layer uses
    this so a stalled allreduce shows up under the task that issued it).
    No-op contextmanager when tracing is disabled."""
    return _span(name, kind, None, **attrs)


def submit_span(kind: str, name: str):
    """PRODUCER span around task/actor submission (driver side)."""
    return _span(f"{kind} {name}", "PRODUCER", None)


def execute_span(kind: str, name: str,
                 carrier: Optional[Dict[str, str]], **attrs):
    """CONSUMER span around task execution (worker side), linked to the
    submitting span via the propagated traceparent."""
    return _span(f"{kind}.execute {name}", "CONSUMER",
                 _extract(carrier), **attrs)


def rpc_client_span(method: str, **attrs):
    """CLIENT span around one framed-RPC round trip.  Only opened when a
    span context is already active, so the control-plane conversation of
    a traced task (submit -> lease -> push -> reply) nests under the
    task's PRODUCER span instead of flooding the trace with orphans."""
    return _span(f"rpc {method}", "CLIENT", None, **attrs)


def rpc_server_span(method: str, carrier: Optional[Dict[str, str]],
                    **attrs):
    """SERVER span around handler execution, linked to the caller's
    CLIENT span via the traceparent carried in the frame meta."""
    return _span(f"rpc.handle {method}", "SERVER", _extract(carrier),
                 **attrs)


# -- built-in file exporter hook --------------------------------------------

_file_lock = threading.Lock()


def setup_file_exporter(config: Optional[Dict[str, Any]] = None) -> None:
    """Startup hook: append finished spans as JSON lines to
    ``config["trace_file"]``."""
    path = (config or {}).get("trace_file")
    if not path:
        return

    def sink(span: Dict[str, Any]) -> None:
        with _file_lock, open(path, "a") as f:
            f.write(json.dumps(span) + "\n")

    configure(sink)


def register_hook(control, hook: str,
                  config: Optional[Dict[str, Any]] = None) -> None:
    """Driver side: record the startup hook so workers apply it too."""
    control.call("kv_put", {
        "ns": KV_NS, "key": "hook",
        "val": json.dumps({"hook": hook, "config": config or {}}).encode(),
        "overwrite": True,
    }, timeout=30.0)


def apply_hook_from_kv(control) -> None:
    """Worker side: pick up and run the registered startup hook."""
    try:
        raw = control.call("kv_get", {"ns": KV_NS, "key": "hook"},
                           timeout=10.0)
    except Exception:
        return
    if not raw:
        return
    try:
        rec = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
        run_hook(rec["hook"], rec.get("config") or {})
    except Exception:
        logger.exception("tracing startup hook failed")


def run_hook(hook: str, config: Optional[Dict[str, Any]] = None) -> None:
    """Import and call a ``module:function`` hook, then enable tracing."""
    import importlib

    mod_name, _, fn_name = hook.partition(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    try:
        fn(config)
    except TypeError:
        fn()
    enable()
