"""Task/actor span tracing with W3C traceparent propagation.

Reference: python/ray/util/tracing/tracing_helper.py — opt-in tracing
that wraps task/actor submission (PRODUCER span) and execution (CONSUMER
span) and propagates the span context inside the task spec, so a
distributed trace stitches across processes.

The recorder is native (this image ships only the opentelemetry API
package, not the SDK): spans carry OTel-shaped fields (trace_id,
span_id, parent_id, kind, ns timestamps) and context crosses processes
as a standard ``traceparent`` header, so exported traces drop into any
OTel pipeline.  Enable with
``ray_tpu.init(_tracing_startup_hook="module:function")`` — the hook
runs in the driver AND every worker (its name travels through the
control KV) and must call ``configure(sink)`` (or use the built-in
``setup_file_exporter`` hook, which appends finished spans as JSON
lines to the configured ``trace_file``) — or by setting
``RAY_TPU_TRACE_SAMPLE`` > 0, which enables tracing with head-based
ratio sampling and no local sink (spans flow to the control plane's
collector only).

Sampling is head-based and deterministic on the trace id: the root
span's process decides once (``trace_id`` low bits vs the ratio), the
decision rides in the traceparent flags byte (``-01`` sampled /
``-00`` not), and every downstream process agrees without coordination.
A sampled-out parent suppresses its whole subtree — context still
propagates so late descendants stay suppressed too.

Central collection: every process with a control-plane client installs
a ``SpanBuffer`` (``ensure_collector``) — a bounded ring drained by a
flush thread into batched framed ``report_spans`` notifies, mirroring
the task-event relay shape (``_private/task_events.py``).  The control
plane stores spans per-trace in the ``_tracing`` KV namespace where
``telemetry/trace_assembly.py`` reassembles them.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import contextvars
import json
import logging
import os
import random
import threading
import time
from typing import Any, Callable, Deque, Dict, Optional

logger = logging.getLogger(__name__)

KV_NS = "_tracing"

_enabled = False
_sink: Optional[Callable[[Dict[str, Any]], None]] = None
# short process label stamped on every span record ("driver", "raylet",
# "worker:<id>") so the assembler can attribute wall time per process
_proc = ""
# contextvar, not thread-local: spans opened inside asyncio Tasks must
# attribute per-Task even though all coroutines share the loop thread
_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None)
# resolved trace_sample ratio; None = not yet read from config
_sample_ratio: Optional[float] = None


def is_enabled() -> bool:
    return _enabled


def configure(sink: Callable[[Dict[str, Any]], None]) -> None:
    """Install a span sink (called once per finished span) and enable."""
    global _enabled, _sink
    _sink = sink
    _enabled = True


def enable() -> None:
    global _enabled
    _enabled = True


def set_process(name: str) -> None:
    """Label this process's spans (driver / raylet / worker:<id>)."""
    global _proc
    _proc = name


# Mersenne Twister, not os.urandom: id generation sits on the per-task
# submit path and urandom is a syscall — under ratio sampling the 99%
# sampled-out tasks must not pay two syscalls each.  Seeded from the OS
# entropy pool at import, unique enough for trace correlation.
_rng = random.Random()


def _new_id(nbytes: int) -> int:
    return _rng.getrandbits(nbytes * 8) or 1


def _current() -> Optional[Dict[str, Any]]:
    return _ctx.get()


# shared sampled-out context/carrier: the 99% path under ratio sampling
# allocates no ids and formats no strings — suppression is the only
# information that has to propagate, so one constant serves every trace
_SUPPRESSED_CTX: Dict[str, Any] = {"trace_id": 0, "span_id": 0,
                                   "sampled": False}
_SUPPRESSED_CARRIER = {"traceparent":
                       "00-" + "0" * 32 + "-" + "0" * 16 + "-00"}
_NULL_CM = contextlib.nullcontext()  # reusable per the contextlib docs


class _Suppressed:
    """Context manager that propagates the sampled-out context (so every
    descendant suppresses itself) with no id generation, no span dict
    and no generator frame — the hot-path shape of a non-sampled span."""

    __slots__ = ("_token",)

    def __enter__(self):
        self._token = _ctx.set(_SUPPRESSED_CTX)
        return None

    def __exit__(self, *exc):
        _ctx.reset(self._token)
        return False


# -- sampling ----------------------------------------------------------------

def _ratio() -> float:
    """trace_sample ratio, read from config once per process."""
    global _sample_ratio
    if _sample_ratio is None:
        try:
            from ray_tpu._private.config import cfg
            _sample_ratio = float(cfg().trace_sample)
        except Exception:
            _sample_ratio = 0.0
    return _sample_ratio


def set_sample_ratio(ratio: Optional[float]) -> None:
    """Pin (or with None, re-resolve from config) the sampling ratio."""
    global _sample_ratio
    _sample_ratio = ratio


def sample_trace(trace_id: int) -> bool:
    """Head-based sampling decision for a new root, deterministic on the
    trace id so every process computes the same answer.  Ratio 0 means
    the sampler is off: tracing was enabled explicitly (hook/configure)
    and records everything, the pre-sampling behavior."""
    ratio = _ratio()
    if ratio <= 0.0 or ratio >= 1.0:
        return True
    return (trace_id & ((1 << 64) - 1)) < int(ratio * (1 << 64))


def maybe_enable_from_config() -> None:
    """Auto-enable tracing when RAY_TPU_TRACE_SAMPLE > 0 — sampled spans
    then flow to the control collector without any startup hook."""
    if not _enabled and _ratio() > 0.0:
        enable()


# -- context propagation -----------------------------------------------------

def inject_context() -> Optional[Dict[str, str]]:
    """Current span context as a W3C traceparent carrier.  The flags
    byte carries the real sampling decision (01 sampled, 00 not) so a
    sampled-out parent suppresses the whole downstream subtree.  All
    suppressed contexts share one constant carrier — downstream only
    ever reads the flags bit, so the ids carry no information."""
    ctx = _current()
    if not _enabled or ctx is None:
        return None
    if not ctx.get("sampled", True):
        return _SUPPRESSED_CARRIER
    return {"traceparent":
            f"00-{ctx['trace_id']:032x}-{ctx['span_id']:016x}-01"}


def frame_traceparent() -> Optional[str]:
    """Traceparent for RPC frame meta — only for SAMPLED contexts.
    Suppressed contexts return None so the per-frame meta dict + string
    formatting cost vanishes from untraced requests; frame-level SERVER
    spans only exist for sampled traces anyway (suppression crosses
    processes in the task spec's carrier, not the frame meta)."""
    ctx = _current()
    if not _enabled or ctx is None or not ctx.get("sampled", True):
        return None
    return f"00-{ctx['trace_id']:032x}-{ctx['span_id']:016x}-01"


def _extract(carrier: Optional[Dict[str, str]]
             ) -> Optional[Dict[str, Any]]:
    tp = (carrier or {}).get("traceparent", "")
    parts = tp.split("-")
    if len(parts) != 4:
        return None
    try:
        return {"trace_id": int(parts[1], 16),
                "span_id": int(parts[2], 16),
                "sampled": bool(int(parts[3], 16) & 0x01)}
    except ValueError:
        return None


def carrier_sampled(carrier: Optional[Dict[str, str]]) -> bool:
    """Cheap hot-path check: does this carrier mark a sampled trace?
    The sampled bit is the flags byte's low bit — the traceparent's
    last hex digit is odd iff sampled, so one suffix probe replaces the
    full split-and-parse on the 99% sampled-out path."""
    if not carrier:
        return False
    return carrier.get("traceparent", "")[-1:] in "13579bdf"


def _emit(record: Dict[str, Any]) -> None:
    if _proc:
        record["proc"] = _proc
    if _sink is not None:
        try:
            _sink(record)
        except Exception:
            logger.exception("span sink failed")
    buf = _buffer
    if buf is not None:
        buf.add(record)


def _format(span: Dict[str, Any]) -> Dict[str, Any]:
    record = dict(span)
    record["trace_id"] = f"{span['trace_id']:032x}"
    record["span_id"] = f"{span['span_id']:016x}"
    if span["parent_id"] is not None:
        record["parent_id"] = f"{span['parent_id']:016x}"
    return record


def _span(name: str, kind: str,
          parent: Optional[Dict[str, Any]], **attrs):
    """Dispatch to the cheapest context manager that preserves the
    sampling semantics.  Sampled-out spans never reach the recording
    generator: an inherited suppressed context is already in place
    (_NULL_CM), an explicit suppressed parent only needs the shared
    suppressed context installed (_Suppressed), and a sampled-out new
    root likewise — no ids minted, no span dict built."""
    if not _enabled:
        return _NULL_CM
    explicit = parent is not None
    if parent is None:
        parent = _current()
    if parent is not None:
        if not parent.get("sampled", True):
            # the contextvar already holds a suppressed context when the
            # parent was inherited from it — nothing to install
            return _Suppressed() if explicit else _NULL_CM
        trace_id = parent["trace_id"]
        parent_sid = parent["span_id"]
    else:
        trace_id = _new_id(16)
        if not sample_trace(trace_id):
            return _Suppressed()
        parent_sid = None
    return _recording_span(name, kind, trace_id, parent_sid, attrs)


@contextlib.contextmanager
def _recording_span(name: str, kind: str, trace_id: int,
                    parent_sid: Optional[int], attrs: Dict[str, Any]):
    span_id = _new_id(8)
    token = _ctx.set({"trace_id": trace_id, "span_id": span_id,
                      "sampled": True})
    span = {
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent_sid,
        "kind": kind,
        "start_ns": time.time_ns(),
        "attributes": {k: v for k, v in attrs.items() if v is not None},
    }
    try:
        yield span
    finally:
        _ctx.reset(token)
        span["end_ns"] = time.time_ns()
        _emit(_format(span))


def record_span(name: str, kind: str, start_ns: int, end_ns: int,
                parent: Optional[Dict[str, Any]], **attrs) -> None:
    """Emit a retro-timed span from already-measured timestamps — the
    hot-path phases (stage-wait, queue-wait, ack-linger) are measured as
    plain clock reads on the fast path and only materialized into spans
    here, after the fact, for sampled traces.  No contextvar is touched.
    Requires an explicit sampled parent: retro phases never mint roots."""
    if not _enabled or parent is None or not parent.get("sampled", True):
        return
    _emit(_format({
        "name": name,
        "trace_id": parent["trace_id"],
        "span_id": _new_id(8),
        "parent_id": parent["span_id"],
        "kind": kind,
        "start_ns": int(start_ns),
        "end_ns": int(end_ns),
        "attributes": {k: v for k, v in attrs.items() if v is not None},
    }))


def span(name: str, kind: str = "INTERNAL", **attrs):
    """Public INTERNAL span, auto-parented to the current context — a
    span opened inside task execution links to the submitting task's
    trace through the propagated traceparent (the collective layer uses
    this so a stalled allreduce shows up under the task that issued it).
    No-op contextmanager when tracing is disabled."""
    return _span(name, kind, None, **attrs)


def phase_span(name: str, carrier: Optional[Dict[str, str]], **attrs):
    """INTERNAL span for a hot-path phase, parented to the trace carried
    in ``carrier`` (a task spec's ``trace_ctx``).  No-op when tracing is
    off or the carrier is absent/unsampled — batch phases only show up
    in traces that already exist."""
    if not _enabled or not carrier_sampled(carrier):
        return _NULL_CM
    return _span(name, "INTERNAL", _extract(carrier), **attrs)


def submit_span(kind: str, name: str):
    """PRODUCER span around task/actor submission (driver side)."""
    return _span(f"{kind} {name}", "PRODUCER", None)


def execute_span(kind: str, name: str,
                 carrier: Optional[Dict[str, str]], **attrs):
    """CONSUMER span around task execution (worker side), linked to the
    submitting span via the propagated traceparent.  A sampled-out
    carrier skips the parse entirely: only the suppressed context needs
    installing so spans opened inside the task suppress themselves."""
    if _enabled and carrier is not None and not carrier_sampled(carrier):
        return _Suppressed()
    return _span(f"{kind}.execute {name}", "CONSUMER",
                 _extract(carrier), **attrs)


def rpc_client_span(method: str, **attrs):
    """CLIENT span around one framed-RPC round trip.  Only opened when a
    span context is already active, so the control-plane conversation of
    a traced task (submit -> lease -> push -> reply) nests under the
    task's PRODUCER span instead of flooding the trace with orphans."""
    ctx = _current()
    if not _enabled or ctx is None:
        return _NULL_CM
    if not ctx.get("sampled", True):
        return _NULL_CM  # suppressed context already active, keep it
    return _span(f"rpc {method}", "CLIENT", None, **attrs)


def rpc_server_span(method: str, carrier: Optional[Dict[str, str]],
                    **attrs):
    """SERVER span around handler execution, linked to the caller's
    CLIENT span via the traceparent carried in the frame meta.  No-op
    without a parseable carrier: a server span never mints a root."""
    if not _enabled or not carrier:
        return _NULL_CM
    tp = carrier.get("traceparent", "")
    if len(tp) != 55:  # 2+1+32+1+16+1+2: not a parseable traceparent
        return _NULL_CM
    if tp[-1:] not in "13579bdf":
        return _Suppressed()  # sampled-out caller: suppress, don't parse
    ctx = _extract(carrier)
    if ctx is None:
        return _NULL_CM
    return _span(f"rpc.handle {method}", "SERVER", ctx, **attrs)


# -- span buffer + batched flusher (central collection) ----------------------

class SpanBuffer:
    """Bounded per-process span ring drained by a daemon flush thread
    into batched ``report_spans`` pushes — same shape as the task-event
    buffer (``_private/task_events.py``): drop-oldest at capacity with
    drop accounting, bounded re-queue when the control plane blips."""

    def __init__(self, transport: Callable[[Dict[str, Any]], None], *,
                 cap: int = 4096, interval_s: float = 0.5,
                 common: Optional[Dict[str, Any]] = None):
        self._transport = transport
        self._cap = cap
        self._common = dict(common or {})
        self._lock = threading.Lock()
        self._spans: Deque[Dict[str, Any]] = collections.deque(
            maxlen=cap)  # guarded-by: _lock
        self._dropped = 0            # guarded-by: _lock
        self._flushed_batches = 0    # guarded-by: _lock
        self._flushed_spans = 0      # guarded-by: _lock
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._flush_loop, name="trace-spans-flush", daemon=True)
        self._interval_s = interval_s
        self._thread.start()

    def add(self, span: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._spans) == self._cap:
                self._dropped += 1  # maxlen evicts the oldest on append
            self._spans.append(span)

    def _flush_loop(self) -> None:
        while not self._stop_evt.wait(self._interval_s):
            self.flush()

    def flush(self) -> None:
        with self._lock:
            if not self._spans and not self._dropped:
                return
            batch = list(self._spans)
            self._spans.clear()
            dropped = self._dropped
            self._dropped = 0
        payload = {"spans": batch, "dropped": dropped,
                   "common": self._common}
        try:
            self._transport(payload)
            with self._lock:
                self._flushed_batches += 1
                self._flushed_spans += len(batch)
        except Exception:
            # control plane unreachable: re-queue (bounded) so a blip
            # doesn't lose the window; anything cut off the front counts
            # as dropped and the count retries with the next success
            with self._lock:
                merged = batch + list(self._spans)
                cut = max(0, len(merged) - self._cap)
                self._spans = collections.deque(merged[cut:],
                                                maxlen=self._cap)
                self._dropped += dropped + cut

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"buffered": len(self._spans),
                    "flushed_batches": self._flushed_batches,
                    "flushed_spans": self._flushed_spans,
                    "dropped": self._dropped}

    def stop(self) -> None:
        self._stop_evt.set()
        self.flush()


_buffer: Optional[SpanBuffer] = None


def ensure_collector(control_client, *, proc: str = "",
                     worker_id: str = "", node_id: str = "",
                     job_id: str = "") -> None:
    """Install the central span collector for this process: enables
    tracing if RAY_TPU_TRACE_SAMPLE asks for it, then (if tracing is on
    and no buffer exists yet) starts a SpanBuffer flushing batched
    ``report_spans`` notifies over the given control-plane client.
    Idempotent; safe to call from driver, raylet, and worker startup."""
    global _buffer
    maybe_enable_from_config()
    if not _enabled or _buffer is not None or control_client is None:
        return
    if proc:
        set_process(proc)
    try:
        from ray_tpu._private.config import cfg
        c = cfg()
        cap = int(getattr(c, "trace_buffer_cap", 4096))
        interval = float(getattr(c, "trace_flush_interval_s", 0.5))
    except Exception:
        cap, interval = 4096, 0.5
    _buffer = SpanBuffer(
        lambda payload: control_client.notify("report_spans", payload),
        cap=cap, interval_s=interval,
        common={"worker_id": worker_id, "node_id": node_id,
                "job_id": job_id, "proc": proc or _proc})


def detach_collector() -> None:
    """Stop the span buffer (final flush included); used at shutdown and
    by tests that cycle init/shutdown in one process."""
    global _buffer
    buf, _buffer = _buffer, None
    if buf is not None:
        try:
            buf.stop()
        except Exception:
            pass


def buffer_stats() -> Optional[Dict[str, int]]:
    buf = _buffer
    return buf.stats() if buf is not None else None


# -- built-in file exporter hook --------------------------------------------

class _FileExporter:
    """Line-oriented JSONL appender holding ONE open handle: the old
    exporter reopened the file and took a global lock per span, which
    serialized every traced worker through a syscall storm.  Writes are
    line-buffered; an explicit flush lands every FLUSH_EVERY spans and
    ``close()`` (atexit-registered) drains so worker exit never
    truncates the trace."""

    FLUSH_EVERY = 64

    def __init__(self, path: str):
        self._lock = threading.Lock()
        self._f = open(path, "a", buffering=1)  # guarded-by: _lock
        self._since_flush = 0                   # guarded-by: _lock
        atexit.register(self.close)

    def __call__(self, span: Dict[str, Any]) -> None:
        line = json.dumps(span) + "\n"
        with self._lock:
            if self._f is None:
                return
            self._f.write(line)
            self._since_flush += 1
            if self._since_flush >= self.FLUSH_EVERY:
                self._since_flush = 0
                self._f.flush()

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            try:
                f.flush()
                f.close()
            except Exception:
                pass


_file_exporter: Optional[_FileExporter] = None


def setup_file_exporter(config: Optional[Dict[str, Any]] = None) -> None:
    """Startup hook: append finished spans as JSON lines to
    ``config["trace_file"]`` through a persistent buffered appender."""
    global _file_exporter
    path = (config or {}).get("trace_file")
    if not path:
        return
    _file_exporter = _FileExporter(path)
    configure(_file_exporter)


def register_hook(control, hook: str,
                  config: Optional[Dict[str, Any]] = None) -> None:
    """Driver side: record the startup hook so workers apply it too."""
    control.call("kv_put", {
        "ns": KV_NS, "key": "hook",
        "val": json.dumps({"hook": hook, "config": config or {}}).encode(),
        "overwrite": True,
    }, timeout=30.0)


def apply_hook_from_kv(control) -> None:
    """Worker side: pick up and run the registered startup hook."""
    try:
        raw = control.call("kv_get", {"ns": KV_NS, "key": "hook"},
                           timeout=10.0)
    except Exception:
        return
    if not raw:
        return
    try:
        rec = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
        run_hook(rec["hook"], rec.get("config") or {})
    except Exception:
        logger.exception("tracing startup hook failed")


def run_hook(hook: str, config: Optional[Dict[str, Any]] = None) -> None:
    """Import and call a ``module:function`` hook, then enable tracing."""
    import importlib

    mod_name, _, fn_name = hook.partition(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    try:
        fn(config)
    except TypeError:
        fn()
    enable()
