"""ActorPool: round-robin work distribution over a fixed set of actors.

Mirrors the reference's ray.util.ActorPool (reference:
python/ray/util/actor_pool.py): submit/map/map_unordered/get_next/
get_next_unordered/has_next/push/pop_idle.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable):
        self._idle: List[Any] = list(actors)
        self._inflight = {}
        self._pending_by_seq = {}
        self._submit_seq = 0
        self._deliver_seq = 0
        self._pending_submits: List[tuple] = []

    # -- submission --------------------------------------------------------

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queued if no actor is idle."""
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._inflight[future] = (self._submit_seq, actor)
            self._pending_by_seq[self._submit_seq] = future
            self._submit_seq += 1
        else:
            self._pending_submits.append((fn, value))

    def map(self, fn: Callable, values: Iterable) -> Iterable:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterable:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- consumption -------------------------------------------------------

    def has_next(self) -> bool:
        return bool(self._inflight)

    def get_next(self, timeout: Optional[float] = None):
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("no more results")
        # skip holes left by earlier unordered consumption
        while (self._deliver_seq not in self._pending_by_seq
               and self._deliver_seq < self._submit_seq):
            self._deliver_seq += 1
        future = self._pending_by_seq[self._deliver_seq]
        if timeout is not None:
            ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
            if not ready:
                # pool state untouched: the caller can retry
                raise TimeoutError("timed out waiting for result")
        del self._pending_by_seq[self._deliver_seq]
        self._deliver_seq += 1
        _, actor = self._inflight.pop(future)
        self._return_actor(actor)
        # a task error propagates but the actor is back in the pool
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: Optional[float] = None):
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("no more results")
        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        i, actor = self._inflight.pop(future)
        del self._pending_by_seq[i]
        # unordered consumption shifts the ordered cursor past holes
        while (self._deliver_seq not in self._pending_by_seq
               and self._deliver_seq < self._submit_seq):
            self._deliver_seq += 1
        self._return_actor(actor)
        return ray_tpu.get(future)

    # -- membership --------------------------------------------------------

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        while self._pending_submits and self._idle:
            fn, v = self._pending_submits.pop(0)
            self.submit(fn, v)

    def push(self, actor) -> None:
        busy = {a for _, a in self._inflight.values()}
        if actor in self._idle or actor in busy:
            raise ValueError("actor already in pool")
        self._return_actor(actor)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits
