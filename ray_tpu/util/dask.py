"""Dask-on-ray_tpu: execute dask task graphs on the cluster.

Reference parity: python/ray/util/dask/ — a dask scheduler
(`ray_dask_get`) that walks the dask graph, submits each task as a Ray
task with its dependencies passed as ObjectRefs, and materializes the
requested keys.  Usage (when dask is installed):

    import dask
    from ray_tpu.util.dask import ray_dask_get
    dask.config.set(scheduler=ray_dask_get)
    ddf.sum().compute()

The scheduler itself only needs the graph *protocol* — a dict of
``key -> computation`` where a computation is a ``(callable, *args)``
tuple, a literal, or a key reference — so it works (and is tested)
without dask installed.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu

__all__ = ["ray_dask_get"]


def _ishashable(x) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def _istask(x) -> bool:
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _execute_task(func, args):
    """Remote body: args arrive with ObjectRefs already materialized by
    the runtime; nested structures were resolved at submit time."""
    return func(*args)


def _resolve(arg, refs: Dict[Hashable, Any], dsk: Dict):
    """Substitute graph keys with their (possibly ObjectRef) results;
    recurse into list/tuple/dict containers like dask.core.subs."""
    if _ishashable(arg) and arg in refs:
        return refs[arg]
    if _istask(arg):
        # nested task: execute inline at submit time semantics would
        # diverge; submit it as its own anonymous node
        func, *fargs = arg
        fargs = [_resolve(a, refs, dsk) for a in fargs]
        return _remote_exec.remote(func, fargs)
    if isinstance(arg, list):
        return [_resolve(a, refs, dsk) for a in arg]
    if isinstance(arg, tuple):
        return tuple(_resolve(a, refs, dsk) for a in arg)
    if isinstance(arg, dict):
        return {k: _resolve(v, refs, dsk) for k, v in arg.items()}
    return arg


@ray_tpu.remote
def _remote_exec(func, args):
    # ObjectRefs nested in containers are materialized here so arbitrary
    # arg shapes work (the runtime only auto-resolves top-level refs)
    def deep(a):
        if isinstance(a, ray_tpu.ObjectRef):
            return ray_tpu.get(a)
        if isinstance(a, list):
            return [deep(x) for x in a]
        if isinstance(a, tuple):
            return tuple(deep(x) for x in a)
        if isinstance(a, dict):
            return {k: deep(v) for k, v in a.items()}
        return a

    return func(*[deep(a) for a in args])


def _toposort(dsk: Dict) -> List[Hashable]:
    seen: Dict[Hashable, int] = {}  # 0=visiting, 1=done
    out: List[Hashable] = []

    def deps_of(val):
        if _ishashable(val) and val in dsk:
            yield val
            return
        if _istask(val):
            for a in val[1:]:
                yield from deps_of(a)
        elif isinstance(val, (list, tuple)):
            for a in val:
                yield from deps_of(a)
        elif isinstance(val, dict):
            for a in val.values():
                yield from deps_of(a)

    def visit(key):
        state = seen.get(key)
        if state == 1:
            return
        if state == 0:
            raise ValueError(f"cycle in dask graph at {key!r}")
        seen[key] = 0
        for dep in deps_of(dsk[key]):
            visit(dep)
        seen[key] = 1
        out.append(key)

    for k in dsk:
        visit(k)
    return out


def ray_dask_get(dsk: Dict, keys, **kwargs):
    """Dask scheduler entry point (reference: util/dask/scheduler.py
    ray_dask_get): every graph node becomes one ray_tpu task; shared
    dependencies run once and flow between tasks as ObjectRefs."""
    refs: Dict[Hashable, Any] = {}
    for key in _toposort(dsk):
        val = dsk[key]
        if _istask(val):
            func, *args = val
            args = [_resolve(a, refs, dsk) for a in args]
            refs[key] = _remote_exec.remote(func, args)
        else:
            refs[key] = _resolve(val, refs, dsk)

    def unpack(ks):
        if isinstance(ks, list):
            return [unpack(k) for k in ks]
        v = refs[ks] if _ishashable(ks) and ks in refs else ks
        return ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef) else v

    return unpack(keys)
