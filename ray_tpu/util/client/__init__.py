"""Remote-driver client mode (reference: python/ray/util/client/ — the
"Ray Client": ray.init("ray://host:port") proxies a driver outside the
cluster through a server-side proxied driver).

Here: ray_tpu.init("ray-tpu://host:port") connects a ClientCore that
duck-types the in-process CoreWorker, so the entire public API (remote
functions, actors, get/put/wait, placement groups, collectives, the
libraries) runs unchanged over one multiplexed TCP connection; objects are
owned by the server-side driver and pinned per-client until released or
disconnect.
"""

from .client_core import ClientCore, parse_client_address
from .server import ClientServer

__all__ = ["ClientCore", "ClientServer", "parse_client_address"]
