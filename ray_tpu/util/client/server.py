"""ClientServer: hosts a server-side proxied driver for remote clients
(reference: python/ray/util/client/server/ — proxier + server-side
specific drivers; see its ARCHITECTURE.md).

One CoreWorker driver serves all clients (objects it owns are pinned
per-client and released on c_release / disconnect); blocking operations
(get/wait/control) run on a worker pool so the RPC loop stays live.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional, Tuple

import cloudpickle

from ray_tpu._private import serialization
from ray_tpu._private.common import GetTimeoutError
from ray_tpu._private.core import CoreWorker, ObjectRef
from ray_tpu._private.protocol import (Client, DaemonPool, Deferred, Server,
                                       ServerConn)

logger = logging.getLogger(__name__)


def _wire(ref: ObjectRef):
    return (ref.id, ref.owner_addr, ref.owner_id)


def _error_reply(e: BaseException):
    try:
        blob = cloudpickle.dumps(e)
    except Exception:
        blob = cloudpickle.dumps(RuntimeError(f"{type(e).__name__}: {e}"))
    return {"__client_error__": True, "error_blob": blob}


class ClientServer:
    """Accepts ray-tpu:// clients and proxies them onto the cluster."""

    def __init__(self, control_addr: Tuple[str, int],
                 host: str = "127.0.0.1", port: int = 10001,
                 raylet_addr: Optional[Tuple[str, int]] = None):
        self.control_addr = tuple(control_addr)
        # locate a raylet + store like a normal driver would
        node_id = None
        store_root = None
        if raylet_addr is None:
            probe = Client(self.control_addr, name="client-server-probe")
            nodes = probe.call("get_nodes", timeout=30.0)
            probe.close()
            alive = [n for n in nodes if n["state"] == "ALIVE"]
            if alive:
                raylet_addr = tuple(alive[0]["addr"])
        if raylet_addr is not None:
            import os

            probe = Client(tuple(raylet_addr), name="client-server-probe2")
            info = probe.call("node_info", timeout=30.0)
            probe.close()
            node_id = info["node_id"]
            if os.path.isdir(info["store_root"]):
                store_root = info["store_root"]
        self.core = CoreWorker(self.control_addr, raylet_addr, mode="driver",
                               node_id=node_id, store_root=store_root)
        self.pool = DaemonPool(max_workers=32, name="client-server")
        self.lock = threading.Lock()
        # conn -> {object_id: ObjectRef} pins keeping client refs alive
        self.pins: Dict[ServerConn, Dict[str, ObjectRef]] = {}
        # conn -> {task_id: ObjectRefGenerator} live proxied streams
        self.streams: Dict[ServerConn, Dict[str, Any]] = {}

        s = self.server = Server(host, port, name="client-server")
        s.handle("c_hello", self.h_hello)
        s.handle("c_bye", lambda c, p: self._drop_conn(c))
        s.handle("c_put", self.h_put, deferred=True)
        s.handle("c_get", self.h_get, deferred=True)
        s.handle("c_wait", self.h_wait, deferred=True)
        s.handle("c_submit_task", self.h_submit_task, deferred=True)
        s.handle("c_create_actor", self.h_create_actor, deferred=True)
        s.handle("c_submit_actor_task", self.h_submit_actor_task,
                 deferred=True)
        s.handle("c_kill_actor", self.h_kill_actor, deferred=True)
        s.handle("c_get_actor_by_name", self.h_get_actor_by_name,
                 deferred=True)
        s.handle("c_release", self.h_release)
        s.handle("c_stream_next", self.h_stream_next, deferred=True)
        s.handle("c_stream_done", self.h_stream_done)
        s.handle("c_stream_release", self.h_stream_release)
        # cross-language surface (C++ client, cpp/): descriptor-named
        # functions, plain-value args/results — the same restriction the
        # reference places on cross-language calls (cross_language.py)
        s.handle("c_xput", self.h_xput, deferred=True)
        s.handle("c_xget", self.h_xget, deferred=True)
        s.handle("c_xsubmit_task", self.h_xsubmit_task, deferred=True)
        s.handle("c_xcreate_actor", self.h_xcreate_actor, deferred=True)
        s.handle("c_xsubmit_actor_task", self.h_xsubmit_actor_task,
                 deferred=True)
        s.handle("c_xwait", self.h_xwait, deferred=True)
        s.handle("c_xkill_actor", self.h_xkill_actor, deferred=True)
        s.handle("c_control", self.h_control, deferred=True)
        s.handle("c_control_notify", self.h_control_notify)
        s.on_disconnect(self._drop_conn)

    # -- lifecycle ---------------------------------------------------------

    def start(self, block: bool = False):
        self.server.start(thread=not block)

    @property
    def addr(self):
        return self.server.addr

    def stop(self):
        self.server.stop()
        self.core.shutdown()
        self.pool.shutdown()  # or its 32 worker threads outlive the server

    def _drop_conn(self, conn: ServerConn):
        with self.lock:
            self.pins.pop(conn, None)  # refs GC -> server releases objects
            gens = self.streams.pop(conn, None)
        if gens:
            for gen in gens.values():
                try:
                    self.core._release_stream(gen.task_id)
                except Exception:
                    pass

    def _pin(self, conn: ServerConn, refs):
        with self.lock:
            table = self.pins.setdefault(conn, {})
            for r in refs:
                table[r.id] = r

    def _deferred(self, d: Deferred, fn):
        def run():
            try:
                d.resolve(fn())
            except BaseException as e:
                d.resolve(_error_reply(e))

        self.pool.submit(run)

    # -- handlers ----------------------------------------------------------

    def h_hello(self, conn, p):
        with self.lock:
            self.pins.setdefault(conn, {})
        return {"job_id": self.core.job_id,
                "control_addr": self.core.control.addr}

    def h_put(self, conn, p, d: Deferred):
        def run():
            value = serialization.loads_inline(p["blob"])
            ref = self.core.put(value)
            self._pin(conn, [ref])
            return _wire(ref)

        self._deferred(d, run)

    def _refs_from_ids(self, conn, ids):
        """Resolve client-sent ids to pinned ObjectRefs (an unpinned id can
        still be fetched by id if the object is alive server-side)."""
        with self.lock:
            table = self.pins.get(conn, {})
            out = []
            for oid in ids:
                r = table.get(oid)
                if r is None:
                    r = ObjectRef(oid, self.core.addr, self.core.worker_id)
                out.append(r)
            return out

    def h_get(self, conn, p, d: Deferred):
        def run():
            refs = self._refs_from_ids(conn, p["ids"])
            try:
                values = self.core.get(refs, timeout=p.get("timeout"))
            except GetTimeoutError as e:
                return {"timeout": True, "error": str(e)}
            return {"blob": serialization.dumps_inline(values)}

        self._deferred(d, run)

    def h_wait(self, conn, p, d: Deferred):
        def run():
            refs = self._refs_from_ids(conn, p["ids"])
            ready, _ = self.core.wait(refs,
                                      num_returns=p.get("num_returns", 1),
                                      timeout=p.get("timeout"))
            return {"ready": [r.id for r in ready]}

        self._deferred(d, run)

    def h_submit_task(self, conn, p, d: Deferred):
        def run():
            fn = cloudpickle.loads(p["fn_blob"])
            args, kwargs = serialization.loads_inline(p["args_blob"])
            refs = self.core.submit_task(
                fn, args, kwargs,
                num_returns=p.get("num_returns", 1),
                resources=p.get("resources"),
                max_retries=p.get("max_retries", 3),
                strategy=p.get("strategy"), pg=p.get("pg"),
                bundle_index=p.get("bundle_index", -1),
                name=p.get("name", ""),
                runtime_env=p.get("runtime_env"),
                generator_backpressure=p.get("generator_backpressure", 0))
            if p.get("num_returns") == "streaming":
                return self._register_stream(conn, refs[0])
            self._pin(conn, refs)
            return [_wire(r) for r in refs]

        self._deferred(d, run)

    def _register_stream(self, conn, gen):
        with self.lock:
            self.streams.setdefault(conn, {})[gen.task_id] = gen
        return {"streaming": gen.task_id}

    def h_create_actor(self, conn, p, d: Deferred):
        def run():
            cls = cloudpickle.loads(p["cls_blob"])
            args, kwargs = serialization.loads_inline(p["args_blob"])
            return self.core.create_actor(
                cls, args, kwargs,
                resources=p.get("resources"), name=p.get("name"),
                max_restarts=p.get("max_restarts", 0),
                max_task_retries=p.get("max_task_retries", 0),
                max_concurrency=p.get("max_concurrency", 1),
                pg=p.get("pg"), bundle_index=p.get("bundle_index", -1),
                detached=p.get("detached", False),
                runtime_env=p.get("runtime_env"),
                namespace=p.get("namespace"),
                strategy=p.get("strategy"))

        self._deferred(d, run)

    def h_submit_actor_task(self, conn, p, d: Deferred):
        def run():
            args, kwargs = serialization.loads_inline(p["args_blob"])
            refs = self.core.submit_actor_task(
                p["actor_id"], p["method"], args, kwargs,
                num_returns=p.get("num_returns", 1))
            if p.get("num_returns") == "streaming":
                return self._register_stream(conn, refs[0])
            self._pin(conn, refs)
            return [_wire(r) for r in refs]

        self._deferred(d, run)

    def h_stream_next(self, conn, p, d: Deferred):
        """One bounded poll for the next stream item: {"ref": wire} |
        {"done": True} | {"timeout": True}.  Runs on a dedicated thread
        (not the shared DaemonPool): a stream's 30 s wait slices would
        otherwise occupy pool workers at ~100% steady state and starve
        get/wait/submit deferreds once streams ≈ pool size."""

        def run():
            try:
                with self.lock:
                    gen = self.streams.get(conn, {}).get(p["task_id"])
                if gen is None:
                    d.resolve({"done": True})
                    return
                try:
                    ref = gen.next_ready(timeout=p.get("timeout", 30.0))
                except StopIteration:
                    with self.lock:
                        self.streams.get(conn, {}).pop(p["task_id"], None)
                    d.resolve({"done": True})
                    return
                except GetTimeoutError:
                    d.resolve({"timeout": True})
                    return
                self._pin(conn, [ref])
                d.resolve({"ref": _wire(ref)})
            except BaseException as e:
                d.resolve(_error_reply(e))

        threading.Thread(target=run, daemon=True,
                         name="client-stream-next").start()

    def h_stream_done(self, conn, p):
        """Non-consuming completed() check (direct-mode semantics: True
        once the task finished and the buffer drained)."""
        with self.lock:
            gen = self.streams.get(conn, {}).get(p["task_id"])
        return True if gen is None else gen.completed()

    def h_stream_release(self, conn, p):
        with self.lock:
            gen = self.streams.get(conn, {}).pop(p["task_id"], None)
        if gen is not None:
            try:
                self.core._release_stream(gen.task_id)
            except Exception:
                pass
        return True

    def h_kill_actor(self, conn, p, d: Deferred):
        self._deferred(d, lambda: self.core.kill_actor(
            p["actor_id"], no_restart=p.get("no_restart", True)))

    def h_get_actor_by_name(self, conn, p, d: Deferred):
        self._deferred(d, lambda: self.core.get_actor_by_name(
            p["name"], namespace=p.get("namespace")))

    def h_release(self, conn, p):
        with self.lock:
            table = self.pins.get(conn)
            if table:
                for oid in p.get("ids", ()):
                    table.pop(oid, None)
        return True

    # -- cross-language handlers (C++ user API, cpp/) ----------------------

    def _xdeferred(self, d: Deferred, fn):
        """Like _deferred but errors travel as protocol-level ERROR
        frames (plain strings) — foreign clients can't unpickle an
        exception blob."""

        def run():
            try:
                d.resolve(fn())
            except BaseException as e:
                d.reject(f"{type(e).__name__}: {e}")

        self.pool.submit(run)

    @staticmethod
    def _resolve_descriptor(descriptor: str):
        """ "pkg.mod:qualname" (or dotted fallback) -> Python object."""
        import importlib

        if ":" in descriptor:
            mod_name, qual = descriptor.split(":", 1)
        else:
            mod_name, _, qual = descriptor.rpartition(".")
            if not mod_name:
                raise ValueError(
                    f"bad cross-language descriptor {descriptor!r}; "
                    f"expected 'pkg.mod:qualname'")
        obj = importlib.import_module(mod_name)
        for part in qual.split("."):
            obj = getattr(obj, part)
        return obj

    @staticmethod
    def _check_plain(value, where: str):
        """Cross-language values must survive a foreign decoder."""
        if value is None or isinstance(value, (bool, int, float, str,
                                               bytes)):
            return
        if isinstance(value, (list, tuple, set)):
            for v in value:
                ClientServer._check_plain(v, where)
            return
        if isinstance(value, dict):
            for k, v in value.items():
                ClientServer._check_plain(k, where)
                ClientServer._check_plain(v, where)
            return
        raise TypeError(
            f"cross-language {where} must be plain "
            f"(None/bool/int/float/str/bytes/list/tuple/dict), "
            f"got {type(value).__name__}")

    def h_xput(self, conn, p, d: Deferred):
        def run():
            self._check_plain(p["value"], "put value")
            ref = self.core.put(p["value"])
            self._pin(conn, [ref])
            return _wire(ref)

        self._xdeferred(d, run)

    def h_xget(self, conn, p, d: Deferred):
        def run():
            refs = self._refs_from_ids(conn, p["ids"])
            try:
                values = self.core.get(refs, timeout=p.get("timeout"))
            except GetTimeoutError:
                return {"timeout": True}
            self._check_plain(values, "task result")
            return {"values": values}

        self._xdeferred(d, run)

    def h_xsubmit_task(self, conn, p, d: Deferred):
        def run():
            fn = self._resolve_descriptor(p["descriptor"])
            args = tuple(p.get("args") or ())
            self._check_plain(list(args), "task args")
            resources = p.get("resources")
            refs = self.core.submit_task(
                fn, args, {},
                num_returns=p.get("num_returns", 1),
                resources=dict(resources) if resources else None,
                max_retries=p.get("max_retries", 3),
                name=p.get("name") or "")
            self._pin(conn, refs)
            return [_wire(r) for r in refs]

        self._xdeferred(d, run)

    def h_xcreate_actor(self, conn, p, d: Deferred):
        def run():
            cls = self._resolve_descriptor(p["descriptor"])
            args = tuple(p.get("args") or ())
            self._check_plain(list(args), "actor args")
            resources = p.get("resources")
            return self.core.create_actor(
                cls, args, {},
                resources=dict(resources) if resources else None,
                name=p.get("name") or None)

        self._xdeferred(d, run)

    def h_xsubmit_actor_task(self, conn, p, d: Deferred):
        def run():
            args = tuple(p.get("args") or ())
            self._check_plain(list(args), "actor task args")
            refs = self.core.submit_actor_task(
                p["actor_id"], p["method"], args, {})
            self._pin(conn, refs)
            return [_wire(r) for r in refs]

        self._xdeferred(d, run)

    def h_xwait(self, conn, p, d: Deferred):
        """Like c_wait, but failures travel as ERROR frames a foreign
        client can read (c_wait's _error_reply is an unpicklable-to-C++
        blob that would read as an empty ready list)."""

        def run():
            refs = self._refs_from_ids(conn, p["ids"])
            num_returns = p.get("num_returns", 1)
            if num_returns > len(refs):
                raise ValueError("num_returns > len(refs)")
            ready, _ = self.core.wait(refs, num_returns=num_returns,
                                      timeout=p.get("timeout"))
            return {"ready": [r.id for r in ready]}

        self._xdeferred(d, run)

    def h_xkill_actor(self, conn, p, d: Deferred):
        self._xdeferred(d, lambda: self.core.kill_actor(
            p["actor_id"], no_restart=p.get("no_restart", True)))

    def h_control(self, conn, p, d: Deferred):
        self._deferred(d, lambda: self.core.control.call(
            p["method"], p.get("payload"), timeout=p.get("timeout") or 60.0))

    def h_control_notify(self, conn, p):
        try:
            self.core.control.notify(p["method"], p.get("payload"))
        except OSError:
            pass
        return True


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--control", required=True, help="host:port of control")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=10001)
    args = ap.parse_args()
    host, port = args.control.rsplit(":", 1)
    srv = ClientServer((host, int(port)), host=args.host, port=args.port)
    logger.info("client server on %s", srv.addr)
    srv.start(block=True)


if __name__ == "__main__":
    main()
