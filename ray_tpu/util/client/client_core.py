"""ClientCore: a CoreWorker stand-in that proxies every operation to a
ClientServer over one TCP connection (reference: python/ray/util/client/
worker.py — the client-side Worker speaking the ray_client protocol).

Duck-types the subset of CoreWorker the API layer and libraries touch:
submit_task / create_actor / submit_actor_task / get / put / wait /
kill_actor / get_actor_by_name / as_future / the serialization ref hooks,
plus a forwarding `control` handle so control-plane consumers (placement
groups, collectives, state API, internal KV) work transparently.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu._private import common, core as core_mod, serialization
from ray_tpu._private.common import GetTimeoutError, RayTpuError
from ray_tpu._private.core import ObjectRef
from ray_tpu._private.protocol import Client, ConnectionLost

CLIENT_SCHEME = "ray-tpu://"

_STREAM_POLL_SLICE = 30.0  # server-side bounded wait per poll


class ClientObjectRefGenerator:
    """Client-mode stand-in for ObjectRefGenerator: each item is fetched
    with bounded server polls (c_stream_next) so a silent stream never
    wedges a server pool thread.  Mirrors the direct-mode surface:
    __next__/next_ready/completed/async iteration/task_id."""

    def __init__(self, cc: "ClientCore", task_id: str):
        self._cc = cc
        self._task_id = task_id
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        ref = self._next(timeout=None)
        if ref is None:
            raise StopIteration
        return ref

    def next_ready(self, timeout: Optional[float] = None) -> ObjectRef:
        ref = self._next(timeout=timeout)
        if ref is None:
            raise StopIteration
        return ref

    def _next(self, timeout: Optional[float]) -> Optional[ObjectRef]:
        if self._done:
            return None
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        first = True
        while True:
            remaining = None if deadline is None \
                else deadline - _time.monotonic()
            if remaining is not None and remaining <= 0:
                # poll at least once: next_ready(0) must return an
                # already-buffered item (direct-mode _next_stream_item
                # checks st.ready before the deadline)
                if not first:
                    raise GetTimeoutError(
                        f"streaming task {self._task_id} produced no item "
                        f"in time")
                remaining = 0.0
            first = False
            poll = _STREAM_POLL_SLICE if remaining is None \
                else min(_STREAM_POLL_SLICE, remaining)
            r = self._cc._call(
                "c_stream_next",
                {"task_id": self._task_id, "timeout": poll},
                timeout=poll + 30.0)
            if r.get("done"):
                self._done = True
                return None
            if r.get("timeout"):
                continue
            return self._cc._mk_ref(r["ref"])

    def completed(self) -> bool:
        if self._done:
            return True
        # non-consuming server check: direct-mode completed() is True as
        # soon as the task is done and the buffer drained, even before
        # the user observes StopIteration
        try:
            return bool(self._cc._call(
                "c_stream_done", {"task_id": self._task_id}, timeout=30.0))
        except Exception:
            return self._done

    def __aiter__(self):
        return self

    async def __anext__(self) -> ObjectRef:
        import asyncio

        loop = asyncio.get_running_loop()
        ref = await loop.run_in_executor(None, self._next, None)
        if ref is None:
            raise StopAsyncIteration
        return ref

    @property
    def task_id(self) -> str:
        return self._task_id

    def __del__(self):
        cc = self._cc
        if cc is not None and not self._done and not cc._shutdown:
            try:
                cc._client.notify("c_stream_release",
                                  {"task_id": self._task_id})
            except Exception:  # incl. ConnectionLost; never raise in __del__
                pass


def parse_client_address(address: str) -> Tuple[str, int]:
    hostport = address[len(CLIENT_SCHEME):]
    host, port = hostport.rsplit(":", 1)
    return host, int(port)


def _to_wire_ref(ref: ObjectRef):
    return (ref.id, ref.owner_addr, ref.owner_id)


class _ControlProxy:
    """Forwarding stand-in for CoreWorker.control (a protocol Client)."""

    def __init__(self, cc: "ClientCore"):
        self._cc = cc

    @property
    def addr(self):
        return self._cc._server_control_addr

    def call(self, method: str, payload: Any = None,
             timeout: Optional[float] = None):
        return self._cc._call("c_control", {"method": method,
                                            "payload": payload,
                                            "timeout": timeout},
                              timeout=(timeout or 60.0) + 30.0)

    def call_async(self, method: str, payload: Any = None):
        return self._cc._client.call_async(
            "c_control", {"method": method, "payload": payload,
                          "timeout": 60.0})

    def notify(self, method: str, payload: Any = None):
        try:
            self._cc._client.notify(
                "c_control_notify", {"method": method, "payload": payload})
        except OSError:
            pass

    @property
    def closed(self):
        return self._cc._shutdown


class ClientCore:
    mode = "client"

    def __init__(self, address: str, connect_timeout: float = 30.0):
        host, port = parse_client_address(address)
        self.worker_id = f"client-{uuid.uuid4().hex[:16]}"
        self.addr = None
        self.node_id = None  # client drivers live outside every node
        self._shutdown = False
        self.lock = threading.RLock()
        self._client = Client((host, port), name="ray-tpu-client",
                              connect_timeout=connect_timeout,
                              on_disconnect=self._on_disconnect)
        hello = self._client.call("c_hello", {"client_id": self.worker_id},
                                  timeout=connect_timeout)
        self.job_id = hello["job_id"]
        self._server_control_addr = tuple(hello["control_addr"])
        self.control = _ControlProxy(self)

    # -- plumbing ----------------------------------------------------------

    def _on_disconnect(self):
        self._shutdown = True

    def _call(self, method: str, payload: Dict[str, Any],
              timeout: Optional[float] = None):
        if self._shutdown:
            raise RayTpuError("client connection closed")
        try:
            r = self._client.call(method, payload, timeout=timeout)
        except ConnectionLost as e:
            self._shutdown = True
            raise RayTpuError(f"client connection lost: {e}") from e
        if isinstance(r, dict) and r.get("__client_error__"):
            raise cloudpickle.loads(r["error_blob"])
        return r

    def _mk_ref(self, wire) -> ObjectRef:
        return ObjectRef(wire[0], wire[1], wire[2])

    # -- serialization hooks (duck-typed from CoreWorker) ------------------

    def _on_borrowed_ref(self, ref: ObjectRef):
        pass  # the server pins on our behalf

    def _pin_for_serialization(self, ref: ObjectRef):
        pass

    def _remove_local_ref(self, ref: ObjectRef):
        if self._shutdown:
            return
        try:
            self._client.notify("c_release", {"ids": [ref.id]})
        except OSError:
            pass

    # -- core API ----------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        blob = serialization.dumps_inline(value)
        wire = self._call("c_put", {"blob": blob}, timeout=300.0)
        return self._mk_ref(wire)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRefs, got {type(r)}")
        r = self._call("c_get", {"ids": [x.id for x in ref_list],
                                 "timeout": timeout},
                       timeout=None if timeout is None else timeout + 30.0)
        if r.get("timeout"):
            raise GetTimeoutError(r.get("error") or "get() timed out")
        values = serialization.loads_inline(r["blob"])
        return values[0] if single else values

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        r = self._call("c_wait", {"ids": [x.id for x in refs],
                                  "num_returns": num_returns,
                                  "timeout": timeout},
                       timeout=None if timeout is None else timeout + 30.0)
        ready_ids = set(r["ready"])
        ready = [x for x in refs if x.id in ready_ids]
        not_ready = [x for x in refs if x.id not in ready_ids]
        return ready, not_ready

    def as_future(self, ref: ObjectRef):
        from concurrent.futures import Future

        fut: Future = Future()

        def run():
            try:
                fut.set_result(self.get(ref))
            except BaseException as e:
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    def submit_task(self, fn, args, kwargs, *, num_returns=1, resources=None,
                    max_retries=3, strategy=None, pg=None, bundle_index=-1,
                    name="", runtime_env=None,
                    generator_backpressure=0) -> List[ObjectRef]:
        common._ensure_picklable_by_value(fn)
        if runtime_env:
            # package local dirs on the CLIENT machine; the server only
            # ever sees content-addressed pkg: URIs
            from ray_tpu._private import runtime_env as rtenv

            runtime_env = rtenv.prepare(runtime_env, self.control)
        payload = {
            "fn_blob": cloudpickle.dumps(fn),
            "args_blob": serialization.dumps_inline((args, kwargs)),
            "num_returns": num_returns,
            "resources": resources,
            "max_retries": max_retries,
            "strategy": strategy,
            "pg": pg,
            "bundle_index": bundle_index,
            "name": name,
            "runtime_env": runtime_env,
            "generator_backpressure": generator_backpressure,
        }
        wires = self._call("c_submit_task", payload, timeout=120.0)
        if isinstance(wires, dict) and "streaming" in wires:
            return [ClientObjectRefGenerator(self, wires["streaming"])]
        return [self._mk_ref(w) for w in wires]

    def create_actor(self, cls, args, kwargs, *, resources=None, name=None,
                     max_restarts=0, max_task_retries=0, max_concurrency=1,
                     pg=None, bundle_index=-1, detached=False,
                     runtime_env=None, namespace=None,
                     strategy=None) -> str:
        common._ensure_picklable_by_value(cls)
        if runtime_env:
            from ray_tpu._private import runtime_env as rtenv

            runtime_env = rtenv.prepare(runtime_env, self.control)
        payload = {
            "cls_blob": cloudpickle.dumps(cls),
            "args_blob": serialization.dumps_inline((args, kwargs)),
            "resources": resources,
            "name": name,
            "max_restarts": max_restarts,
            "max_task_retries": max_task_retries,
            "max_concurrency": max_concurrency,
            "pg": pg,
            "bundle_index": bundle_index,
            "detached": detached,
            "runtime_env": runtime_env,
            "namespace": namespace,
            "strategy": strategy,
        }
        return self._call("c_create_actor", payload, timeout=120.0)

    def submit_actor_task(self, actor_id: str, method_name: str, args,
                          kwargs, num_returns: int = 1) -> List[ObjectRef]:
        payload = {
            "actor_id": actor_id,
            "method": method_name,
            "args_blob": serialization.dumps_inline((args, kwargs)),
            "num_returns": num_returns,
        }
        wires = self._call("c_submit_actor_task", payload, timeout=120.0)
        if isinstance(wires, dict) and "streaming" in wires:
            return [ClientObjectRefGenerator(self, wires["streaming"])]
        return [self._mk_ref(w) for w in wires]

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        self._call("c_kill_actor", {"actor_id": actor_id,
                                    "no_restart": no_restart}, timeout=60.0)

    def get_actor_by_name(self, name: str, namespace=None):
        return self._call("c_get_actor_by_name",
                          {"name": name, "namespace": namespace},
                          timeout=60.0)

    def available_resources(self) -> Dict[str, float]:
        r = self.control.call("cluster_resources", {}, timeout=30.0)
        return r["available"]

    def cluster_resources(self) -> Dict[str, float]:
        r = self.control.call("cluster_resources", {}, timeout=30.0)
        return r["total"]

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        try:
            self._client.notify("c_bye", {})
        except OSError:
            pass
        self._client.close()
