"""Drop-in `multiprocessing.Pool` backed by ray_tpu actors.

Analog of the reference's ray.util.multiprocessing (reference:
python/ray/util/multiprocessing/pool.py): the Pool API (map/imap/starmap/
apply, sync + async variants) over a pool of actor processes, so existing
multiprocessing code scales past one node by changing an import.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool


class AsyncResult:
    """multiprocessing.pool.AsyncResult equivalent."""

    def __init__(self, refs, single: bool, callback=None,
                 error_callback=None):
        self._refs = refs
        self._single = single
        self._value = None
        self._error = None
        self._done = threading.Event()
        t = threading.Thread(target=self._wait_thread,
                             args=(callback, error_callback), daemon=True)
        t.start()

    def _wait_thread(self, callback, error_callback):
        try:
            vals = ray_tpu.get(list(self._refs))
            self._value = vals[0] if self._single else vals
            if callback is not None:
                callback(self._value)
        except Exception as e:
            self._error = e
            if error_callback is not None:
                error_callback(e)
        finally:
            self._done.set()

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._value

    def wait(self, timeout: Optional[float] = None):
        self._done.wait(timeout)

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self._done.is_set():
            raise ValueError("result not ready")
        return self._error is None


@ray_tpu.remote
class _PoolWorker:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run(self, fn, args, kwargs):
        return fn(*args, **(kwargs or {}))

    def run_chunk(self, fn, chunk):
        return [fn(*args) for args in chunk]


class Pool:
    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources()
                                   .get("CPU", 1)))
        self._size = processes
        self._actors = [_PoolWorker.remote(initializer, initargs)
                        for _ in range(processes)]
        self._idx = itertools.count()
        self._closed = False

    # -- apply -------------------------------------------------------------

    def _next_actor(self):
        return self._actors[next(self._idx) % self._size]

    def apply(self, func: Callable, args=(), kwds=None):
        return ray_tpu.get(
            self._next_actor().run.remote(func, args, kwds))

    def apply_async(self, func, args=(), kwds=None, callback=None,
                    error_callback=None) -> AsyncResult:
        ref = self._next_actor().run.remote(func, args, kwds)
        return AsyncResult([ref], single=True, callback=callback,
                           error_callback=error_callback)

    # -- map ---------------------------------------------------------------

    def _chunks(self, iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._size * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], chunksize

    def _map_refs(self, func, star_items, chunksize):
        chunks, _ = self._chunks(star_items, chunksize)
        return [self._actors[i % self._size].run_chunk.remote(func, c)
                for i, c in enumerate(chunks)]

    def map(self, func, iterable: Iterable, chunksize=None) -> List[Any]:
        refs = self._map_refs(func, [(x,) for x in iterable], chunksize)
        return [v for chunk in ray_tpu.get(refs) for v in chunk]

    def map_async(self, func, iterable, chunksize=None, callback=None,
                  error_callback=None) -> AsyncResult:
        refs = self._map_refs(func, [(x,) for x in iterable], chunksize)

        # flatten on completion
        class _FlatResult(AsyncResult):
            def _wait_thread(self, cb, ecb):
                try:
                    chunks = ray_tpu.get(list(self._refs))
                    self._value = [v for c in chunks for v in c]
                    if cb:
                        cb(self._value)
                except Exception as e:
                    self._error = e
                    if ecb:
                        ecb(e)
                finally:
                    self._done.set()

        return _FlatResult(refs, single=False, callback=callback,
                           error_callback=error_callback)

    def starmap(self, func, iterable: Iterable, chunksize=None):
        refs = self._map_refs(func, list(iterable), chunksize)
        return [v for chunk in ray_tpu.get(refs) for v in chunk]

    def imap(self, func, iterable, chunksize=1):
        chunks, _ = self._chunks([(x,) for x in iterable], chunksize)
        refs = [self._actors[i % self._size].run_chunk.remote(func, c)
                for i, c in enumerate(chunks)]
        for ref in refs:
            for v in ray_tpu.get(ref):
                yield v

    def imap_unordered(self, func, iterable, chunksize=1):
        chunks, _ = self._chunks([(x,) for x in iterable], chunksize)
        pending = {self._actors[i % self._size]
                   .run_chunk.remote(func, c): None
                   for i, c in enumerate(chunks)}
        refs = list(pending)
        while refs:
            ready, refs = ray_tpu.wait(refs, num_returns=1)
            for v in ray_tpu.get(ready[0]):
                yield v

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = []

    def join(self):
        if not self._closed:
            raise ValueError("join() before close()")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
