"""Scheduling strategy objects accepted by @remote(scheduling_strategy=...).

Mirrors the reference (reference: python/ray/util/scheduling_strategies.py —
PlacementGroupSchedulingStrategy :15, NodeAffinitySchedulingStrategy :41,
NodeLabelSchedulingStrategy :135 with In/NotIn/Exists/DoesNotExist label
match operators).
"""

from __future__ import annotations

from typing import Dict, Optional


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group, placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class In:
    """Label value is one of the given values."""

    def __init__(self, *values: str):
        self.values = [str(v) for v in values]

    def to_wire(self):
        return ("in", self.values)


class NotIn:
    """Label value is none of the given values."""

    def __init__(self, *values: str):
        self.values = [str(v) for v in values]

    def to_wire(self):
        return ("not_in", self.values)


class Exists:
    """Label key is present on the node."""

    def to_wire(self):
        return ("exists", [])


class DoesNotExist:
    """Label key is absent from the node."""

    def to_wire(self):
        return ("does_not_exist", [])


def _exprs_to_wire(d: Optional[Dict]) -> list:
    out = []
    for key, op in (d or {}).items():
        if isinstance(op, (In, NotIn, Exists, DoesNotExist)):
            kind, values = op.to_wire()
        else:  # bare value sugar: {"tpu-version": "v5e"} == In("v5e")
            kind, values = "in", [str(op)]
        out.append((key, kind, values))
    return out


class NodeLabelSchedulingStrategy:
    """Target nodes by label (reference: scheduling_strategies.py:135).
    `hard` requirements must match; among matching nodes, ones that also
    satisfy `soft` are preferred.  Nodes carry labels from their raylet
    registration (TPU topology labels are set automatically —
    _private/accelerators.py)."""

    def __init__(self, hard: Optional[Dict] = None,
                 soft: Optional[Dict] = None):
        if not hard and not soft:
            raise ValueError(
                "NodeLabelSchedulingStrategy needs hard or soft labels")
        self.hard = hard or {}
        self.soft = soft or {}

    def to_wire(self):
        return {"kind": "node_label",
                "hard": _exprs_to_wire(self.hard),
                "soft": _exprs_to_wire(self.soft)}


__all__ = ["PlacementGroupSchedulingStrategy",
           "NodeAffinitySchedulingStrategy",
           "NodeLabelSchedulingStrategy",
           "In", "NotIn", "Exists", "DoesNotExist"]
