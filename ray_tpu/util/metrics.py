"""User-defined metrics API: Counter / Gauge / Histogram.

Analog of the reference's ray.util.metrics (reference:
python/ray/util/metrics.py backed by the C++ opencensus registry,
src/ray/stats/metric.h): metrics register in a process-local registry; a
flusher thread publishes snapshots into the control-plane KV under the
``_metrics`` namespace keyed by worker id; the dashboard merges all
snapshots and serves Prometheus text exposition (reference: metric
exporter -> agent -> Prometheus endpoint).
"""

from __future__ import annotations

import json
import pickle
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

METRICS_NS = "_metrics"
FLUSH_INTERVAL_S = 2.0

_DEFAULT_HIST_BOUNDARIES = [
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000,
]


class _Registry:
    """Process-local metric registry.

    Holds metrics by *weak* reference: user code that drops its last
    strong ref (e.g. metrics created in a prior init/shutdown epoch)
    gets swept instead of flushing stale series forever.
    """

    def __init__(self):
        self.lock = threading.Lock()
        self.metrics: List["weakref.ref[Metric]"] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def register(self, metric: "Metric"):
        with self.lock:
            self.metrics.append(weakref.ref(metric))
        self._ensure_flusher()

    def deregister(self, metric: "Metric"):
        """Explicitly drop a metric from future snapshots."""
        with self.lock:
            self.metrics = [r for r in self.metrics
                            if r() is not None and r() is not metric]

    def _live(self) -> List["Metric"]:
        """Prune dead refs; caller must hold self.lock."""
        live, refs = [], []
        for r in self.metrics:
            m = r()
            if m is not None:
                live.append(m)
                refs.append(r)
        self.metrics = refs
        return live

    def restart_if_needed(self):
        """Re-arm the flusher after a shutdown()/init() cycle so metrics
        created in a previous epoch keep flushing."""
        self._ensure_flusher()

    def snapshot(self) -> List[Dict]:
        with self.lock:
            return [m._snapshot() for m in self._live()]

    def _ensure_flusher(self):
        with self.lock:
            if self._thread is not None:
                return
            if not self._live():
                return
            stop = self._stop = threading.Event()  # fresh after a stop()
            self._thread = threading.Thread(
                target=self._flush_loop, args=(stop,),
                name="metrics-flush", daemon=True)
            self._thread.start()

    def _flush_loop(self, stop: threading.Event):
        while not stop.wait(FLUSH_INTERVAL_S):
            try:
                self.flush()
            except Exception:
                pass  # never let a flush race with shutdown kill the loop

    def stop(self):
        """Stop the flusher (called from ray_tpu.shutdown()); a later
        metric registration restarts it."""
        with self.lock:
            self._stop.set()
            thread, self._thread = self._thread, None
            self._live()  # sweep dead epoch refs while we hold the lock
        if thread is not None:
            # the set event makes stop.wait return immediately, so this
            # join is bounded by one in-flight flush at most
            thread.join(timeout=1.0)

    def flush(self):
        # non-raising core lookup: the flusher may fire after shutdown
        from ray_tpu._private import core as core_mod

        core = core_mod._current_core
        if core is None or getattr(core, "_shutdown", False):
            return
        snap = self.snapshot()
        if not snap:
            return
        try:
            core.control.call("kv_put", {
                "ns": METRICS_NS,
                "key": core.worker_id,
                "val": pickle.dumps({"ts": time.time(), "metrics": snap}),
            }, timeout=5.0)
        except Exception:
            pass


_registry = _Registry()


def collect_cluster_metrics(control_client) -> List[Dict]:
    """Merge every process's last snapshot (dashboard-side helper)."""
    merged: List[Dict] = []
    try:
        keys = control_client.call("kv_keys",
                                   {"ns": METRICS_NS, "prefix": ""},
                                   timeout=5.0)
        for k in keys:
            raw = control_client.call("kv_get",
                                      {"ns": METRICS_NS, "key": k},
                                      timeout=5.0)
            if raw:
                snap = pickle.loads(raw)
                for m in snap["metrics"]:
                    m["worker_id"] = k
                    merged.append(m)
    except Exception:
        pass
    return merged


def control_stats_metrics(stats: Dict) -> List[Dict]:
    """Synthesize ``ray_tpu_control_*`` metric dicts from one
    ``control_stats`` RPC reply.

    The control daemon has no CoreWorker, so it cannot flush through the
    KV path like user processes do — the dashboard calls this instead and
    merges the result into ``/metrics`` alongside the cluster snapshots.
    Output shape matches registry snapshots (prometheus_text input).
    """
    from ray_tpu._private.rpc_stats import BOUNDS_MS

    out: List[Dict] = []

    def metric(name: str, type_: str, desc: str, series: Dict,
               boundaries: Optional[List[float]] = None):
        if not series:
            return
        m = {"name": name, "type": type_, "description": desc,
             "series": series, "worker_id": "control"}
        if boundaries is not None:
            m["boundaries"] = boundaries
        out.append(m)

    def key(**tags) -> str:
        return json.dumps(tags, sort_keys=True)

    def hist_val(snap: Dict) -> Tuple[List[int], float, int]:
        # LatencyHist snapshot -> (bucket_counts, sum, count); the
        # overflow bucket folds into +Inf via the total count
        return (list(snap["buckets"][:len(BOUNDS_MS)]),
                snap["sum_ms"], snap["count"])

    bounds = list(BOUNDS_MS)
    counts: Dict[str, float] = {}
    errors: Dict[str, float] = {}
    inflight: Dict[str, float] = {}
    rpc_bytes: Dict[str, float] = {}
    budget_exc: Dict[str, float] = {}
    handle_h: Dict[str, Tuple] = {}
    queue_h: Dict[str, Tuple] = {}
    for method, s in (stats.get("handlers") or {}).items():
        k = key(Method=method)
        counts[k] = s.get("count", 0)
        errors[k] = s.get("errors", 0)
        inflight[k] = s.get("in_flight", 0)
        rpc_bytes[key(Method=method, Direction="in")] = s.get("bytes_in", 0)
        rpc_bytes[key(Method=method, Direction="out")] = s.get("bytes_out", 0)
        if "budget_exceeded" in s:
            budget_exc[k] = s["budget_exceeded"]
        if s.get("handle_ms"):
            handle_h[k] = hist_val(s["handle_ms"])
        if s.get("queue_ms"):
            queue_h[k] = hist_val(s["queue_ms"])
    metric("ray_tpu_control_rpc_total", "counter",
           "RPCs dispatched per control-plane handler", counts)
    metric("ray_tpu_control_rpc_errors_total", "counter",
           "Handler invocations that raised", errors)
    metric("ray_tpu_control_rpc_in_flight", "gauge",
           "Requests currently being handled", inflight)
    metric("ray_tpu_control_rpc_bytes_total", "counter",
           "Request/reply payload bytes per handler", rpc_bytes)
    metric("ray_tpu_control_rpc_budget_exceeded_total", "counter",
           "Handler completions over their latency budget", budget_exc)
    metric("ray_tpu_control_rpc_handle_ms", "histogram",
           "Handler execution latency (dispatch start -> reply)",
           handle_h, bounds)
    metric("ray_tpu_control_rpc_queue_ms", "histogram",
           "Dispatch-queue wait (frame received -> dispatch start)",
           queue_h, bounds)

    loop = stats.get("loop") or {}
    if loop.get("lag_ms"):
        metric("ray_tpu_control_loop_lag_ms", "histogram",
               "Event-loop tick lag (scheduled vs actual)",
               {key(): hist_val(loop["lag_ms"])}, bounds)

    kv_ops: Dict[str, float] = {}
    kv_bytes: Dict[str, float] = {}
    for ns, s in (stats.get("kv") or {}).items():
        kv_ops[key(Namespace=ns)] = s.get("ops", 0)
        kv_bytes[key(Namespace=ns, Direction="in")] = s.get("bytes_in", 0)
        kv_bytes[key(Namespace=ns, Direction="out")] = s.get("bytes_out", 0)
    metric("ray_tpu_control_kv_ops_total", "counter",
           "KV operations per namespace", kv_ops)
    metric("ray_tpu_control_kv_bytes_total", "counter",
           "KV payload bytes per namespace", kv_bytes)

    pub: Dict[str, float] = {}
    deliv: Dict[str, float] = {}
    pdrop: Dict[str, float] = {}
    for topic, s in (stats.get("pubsub") or {}).items():
        k = key(Topic=topic)
        pub[k] = s.get("publishes", 0)
        deliv[k] = s.get("deliveries", 0)
        pdrop[k] = s.get("dropped_subscribers", 0)
    metric("ray_tpu_control_pubsub_publishes_total", "counter",
           "Messages published per topic", pub)
    metric("ray_tpu_control_pubsub_deliveries_total", "counter",
           "Per-subscriber deliveries per topic", deliv)
    metric("ray_tpu_control_pubsub_dropped_subscribers_total", "counter",
           "Deliveries dropped on dead subscriber connections", pdrop)

    ev = stats.get("events") or {}
    if ev:
        metric("ray_tpu_control_event_queue_depth", "gauge",
               "Buffered task-event batches awaiting drain",
               {key(): ev.get("queue_depth", 0)})
        metric("ray_tpu_control_task_events_dropped_total", "counter",
               "Task events dropped cluster-wide",
               {key(): ev.get("dropped", 0)})
    nodes = stats.get("nodes") or {}
    if nodes:
        metric("ray_tpu_control_nodes_alive", "gauge",
               "Nodes currently ALIVE", {key(): nodes.get("alive", 0)})
    return out


def prometheus_text(metric_dicts: List[Dict]) -> str:
    """Render merged snapshots in Prometheus exposition format."""
    by_name: Dict[str, List[Dict]] = {}
    for m in metric_dicts:
        by_name.setdefault(m["name"], []).append(m)
    lines = []
    for name, ms in sorted(by_name.items()):
        kind = ms[0]["type"]
        prom_type = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram"}[kind]
        desc = ms[0].get("description", "")
        lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {prom_type}")
        for m in ms:
            for tags_json, value in m["series"].items():
                tags = json.loads(tags_json)
                tags["WorkerId"] = m.get("worker_id", "")[:16]
                label = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
                if kind == "histogram":
                    counts, total, num = value
                    acc = 0
                    for b, c in zip(m["boundaries"], counts):
                        acc += c
                        lines.append(
                            f'{name}_bucket{{{label},le="{b}"}} {acc}')
                    lines.append(
                        f'{name}_bucket{{{label},le="+Inf"}} {num}')
                    lines.append(f"{name}_sum{{{label}}} {total}")
                    lines.append(f"{name}_count{{{label}}} {num}")
                else:
                    lines.append(f"{name}{{{label}}} {value}")
    return "\n".join(lines) + "\n"


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._series: Dict[str, object] = {}  # json(tags) -> value
        _registry.register(self)

    def deregister(self):
        """Remove this metric from the registry (stops future flushes)."""
        _registry.deregister(self)

    def set_default_tags(self, default_tags: Dict[str, str]):
        self._default_tags = dict(default_tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> str:
        merged = {**self._default_tags, **(tags or {})}
        extra = set(merged) - set(self._tag_keys)
        if extra:
            raise ValueError(f"tags {extra} not in tag_keys "
                             f"{self._tag_keys} of metric {self._name}")
        return json.dumps(merged, sort_keys=True)

    @property
    def info(self) -> Dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys,
                "default_tags": dict(self._default_tags)}


class Counter(Metric):
    """Monotonic counter (reference: util/metrics.py Counter)."""

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value <= 0:
            raise ValueError("Counter.inc requires value > 0")
        k = self._key(tags)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value

    def _snapshot(self):
        with self._lock:
            return {"name": self._name, "type": "counter",
                    "description": self._description,
                    "series": dict(self._series)}


class Gauge(Metric):
    """Last-value gauge."""

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            self._series[k] = float(value)

    def _snapshot(self):
        with self._lock:
            return {"name": self._name, "type": "gauge",
                    "description": self._description,
                    "series": dict(self._series)}


class Histogram(Metric):
    """Bucketed histogram; series value = (bucket_counts, sum, count)."""

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        self._boundaries = sorted(boundaries or _DEFAULT_HIST_BOUNDARIES)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            counts, total, num = self._series.get(
                k, ([0] * len(self._boundaries), 0.0, 0))
            counts = list(counts)
            for i, b in enumerate(self._boundaries):
                if value <= b:
                    counts[i] += 1
                    break
            self._series[k] = (counts, total + value, num + 1)

    def _snapshot(self):
        with self._lock:
            return {"name": self._name, "type": "histogram",
                    "description": self._description,
                    "boundaries": list(self._boundaries),
                    "series": dict(self._series)}
