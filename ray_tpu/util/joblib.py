"""joblib backend running joblib tasks on the cluster.

Analog of the reference's ray.util.joblib (reference:
python/ray/util/joblib/__init__.py register_ray +
ray_backend.py RayBackend): `register_ray_tpu()` then
``with joblib.parallel_backend("ray_tpu"):`` routes scikit-learn / joblib
``Parallel`` workloads through ray_tpu tasks.
"""

from __future__ import annotations


def register_ray_tpu():
    from joblib import register_parallel_backend

    register_parallel_backend("ray_tpu", _make_backend)


def _make_backend():
    """Build lazily so importing this module never requires joblib."""
    from joblib._parallel_backends import MultiprocessingBackend

    import ray_tpu
    from ray_tpu.util.multiprocessing import Pool

    class RayTpuBackend(MultiprocessingBackend):
        """joblib backend on the ray_tpu multiprocessing Pool (the
        reference subclasses MultiprocessingBackend the same way)."""

        supports_timeout = True

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **kwargs):
            n_jobs = self.effective_n_jobs(n_jobs)
            self.parallel = parallel
            self._pool = Pool(processes=n_jobs)
            return n_jobs

        def effective_n_jobs(self, n_jobs):
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs is None or n_jobs == -1:
                return cpus
            return max(1, min(n_jobs, cpus))

        def terminate(self):
            if getattr(self, "_pool", None) is not None:
                self._pool.terminate()
                self._pool = None

        def _get_pool(self):
            return self._pool

    return RayTpuBackend()
