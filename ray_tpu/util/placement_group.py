"""Placement groups: gang reservation of resource bundles across nodes.

Public API mirroring the reference (reference: python/ray/util/
placement_group.py:41 PlacementGroup, :145 placement_group()), backed by the
control plane's 2-phase PREPARE/COMMIT bundle reservation (reference:
src/ray/raylet/placement_group_resource_manager.h:54-61).  Strategies:
PACK / SPREAD / STRICT_PACK / STRICT_SPREAD; on TPU clusters the planner
prefers keeping PACK bundles on one ICI-connected slice (nodes sharing a
`tpu_slice` label).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from .._private import common
from .._private.core import current_core

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    """Handle to a (possibly still-scheduling) placement group."""

    def __init__(self, pg_id: str,
                 bundles: Optional[List[Dict[str, float]]] = None,
                 create_future=None):
        self.id = pg_id
        self._bundles = bundles
        self._create_future = create_future  # never pickled (__reduce__)

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        if self._bundles is None:
            view = self._view()
            self._bundles = view["bundles"] if view else []
        return self._bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def _view(self):
        return current_core().control.call("get_pg", {"pg_id": self.id})

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until all bundles are reserved (or the group failed).

        The reference returns an ObjectRef from a probe task scheduled in
        bundle 0 (placement_group.py:75); here the create RPC's deferred
        reply resolves exactly when scheduling finishes, so the handle
        that created the group waits on that — no poll interval in the
        churn path.  Deserialized handles fall back to a state poll.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        fut = self._create_future
        if fut is not None:
            from concurrent.futures import TimeoutError as FutTimeout

            # the future is a wait signal only — state is then read live
            # below (the reply snapshot could predate a node loss or a
            # concurrent remove_placement_group)
            try:
                fut.result(timeout=timeout)
            except FutTimeout:
                return False
            except Exception:
                pass  # control hiccup: the poll decides
            self._create_future = None
        while True:
            view = self._view()
            if view is None:
                raise ValueError(f"placement group {self.id} does not exist")
            if view["state"] == "ALIVE":
                return True
            if view["state"] == "DEAD":
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.05)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))

    def __repr__(self):
        return f"PlacementGroup(id={self.id})"


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None,
                    ) -> PlacementGroup:
    """Asynchronously create a placement group (reference:
    util/placement_group.py:145)."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("bundles must be non-empty")
    for b in bundles:
        if not isinstance(b, dict) or not b:
            raise ValueError(f"each bundle must be a non-empty dict, got {b!r}")
        if any(v < 0 for v in b.values()):
            raise ValueError(f"bundle resources must be >= 0: {b!r}")
    pgid = common.placement_group_id()
    core = current_core()
    # async create: the control plane schedules in the background; handle is
    # usable immediately (tasks against it queue until ALIVE).  The reply
    # resolves when scheduling finishes — ready() consumes it.
    fut = core.control.call_async("create_pg", {
        "pg_id": pgid, "bundles": bundles, "strategy": strategy,
        "name": name, "detached": lifetime == "detached",
    })
    return PlacementGroup(pgid, list(bundles), create_future=fut)


def remove_placement_group(pg: PlacementGroup) -> None:
    """Release all bundles and kill actors/tasks scheduled in them."""
    current_core().control.call("remove_pg", {"pg_id": pg.id}, timeout=30.0)


def get_placement_group(name: str) -> PlacementGroup:
    view = current_core().control.call("get_pg", {"pg_id": None, "name": name})
    if view is None:
        raise ValueError(f"no placement group named {name!r}")
    return PlacementGroup(view["pg_id"], view["bundles"])


def placement_group_table() -> Dict[str, Dict]:
    """All placement groups, keyed by id (reference:
    util/placement_group.py placement_group_table)."""
    views = current_core().control.call("list_pgs", {})
    return {v["pg_id"]: v for v in views}
